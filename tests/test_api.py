"""API group tests: config normalize/validate matrix, decoders, CR types.

Modeled on the reference's api tests (api/.../sharing_test.go MPS
memory-limit normalization; cmd/webhook/main_test.go decode matrix).
"""

import pytest

from k8s_dra_driver_gpu_tpu.api import (
    AllocationMode,
    ComputeDomain,
    ComputeDomainChannelConfig,
    ComputeDomainClique,
    ComputeDomainDaemonConfig,
    ComputeDomainNode,
    DecodeError,
    MultiTenancyConfig,
    PassthroughConfig,
    Sharing,
    SubSliceConfig,
    TimeSlicingConfig,
    TpuConfig,
    ValidationError,
    nonstrict_decode,
    strict_decode,
)
from k8s_dra_driver_gpu_tpu.api.decode import encode_config


def params(kind: str, **fields) -> dict:
    return {"apiVersion": "resource.tpu.dra/v1beta1", "kind": kind, **fields}


class TestSharing:
    def test_default_normalizes_to_time_slicing(self):
        s = Sharing()
        s.normalize()
        s.validate()
        assert s.is_time_slicing
        assert s.time_slicing.interval == "Default"

    def test_bad_interval(self):
        s = Sharing(time_slicing=TimeSlicingConfig(interval="Turbo"))
        s.normalize()
        with pytest.raises(ValidationError):
            s.validate()

    def test_strategy_member_mismatch(self):
        s = Sharing(strategy="TimeSlicing",
                    multi_tenancy=MultiTenancyConfig())
        with pytest.raises(ValidationError):
            s.validate()
        s = Sharing(strategy="MultiTenancy",
                    time_slicing=TimeSlicingConfig())
        with pytest.raises(ValidationError):
            s.validate()

    def test_multi_tenancy_requires_config(self):
        s = Sharing(strategy="MultiTenancy")
        with pytest.raises(ValidationError):
            s.validate()


class TestMultiTenancy:
    def test_hbm_limit_normalization(self):
        # The default limit folds into the per-device map (reference
        # sharing.go:190-220 normalization).
        mt = MultiTenancyConfig(hbm_limit="8Gi",
                                per_device_hbm_limits={"chip-1": "4Gi"})
        mt.normalize()
        mt.validate()
        assert mt.hbm_limit_bytes_for("chip-1") == 4 << 30
        assert mt.hbm_limit_bytes_for("chip-0") == 8 << 30

    def test_explicit_wildcard_wins_over_default(self):
        mt = MultiTenancyConfig(hbm_limit="8Gi",
                                per_device_hbm_limits={"*": "2Gi"})
        mt.normalize()
        assert mt.hbm_limit_bytes_for("chip-0") == 2 << 30

    def test_invalid_limits(self):
        for bad in ("8G", "-4Gi", "lots"):
            mt = MultiTenancyConfig(hbm_limit=bad)
            mt.normalize()
            with pytest.raises(ValidationError):
                mt.validate()
        # Empty string means unset, not invalid.
        mt = MultiTenancyConfig(hbm_limit="")
        mt.normalize()
        mt.validate()

    def test_max_clients(self):
        mt = MultiTenancyConfig(max_clients=0)
        with pytest.raises(ValidationError):
            mt.validate()

    def test_no_limit_returns_none(self):
        mt = MultiTenancyConfig()
        mt.normalize()
        assert mt.hbm_limit_bytes_for("chip-0") is None


class TestConfigs:
    def test_tpu_config_default(self):
        c = TpuConfig()
        c.normalize()
        c.validate()
        assert c.sharing.is_time_slicing

    def test_passthrough_modes(self):
        c = PassthroughConfig(iommu_mode="iommufd")
        c.normalize()
        c.validate()
        c = PassthroughConfig(iommu_mode="weird")
        with pytest.raises(ValidationError):
            c.validate()

    def test_channel_config(self):
        c = ComputeDomainChannelConfig(domain_id="abc")
        c.normalize()
        c.validate()
        assert c.allocation_mode == AllocationMode.SINGLE.value
        with pytest.raises(ValidationError):
            ComputeDomainChannelConfig(domain_id="").validate()
        bad = ComputeDomainChannelConfig(domain_id="abc",
                                         allocation_mode="Some")
        with pytest.raises(ValidationError):
            bad.validate()

    def test_daemon_config(self):
        with pytest.raises(ValidationError):
            ComputeDomainDaemonConfig().validate()


class TestDecoders:
    def test_roundtrip_tpu_config(self):
        p = params("TpuConfig", sharing={
            "strategy": "MultiTenancy",
            "multiTenancy": {"maxClients": 4, "hbmLimit": "8Gi"},
        })
        cfg = strict_decode(p)
        assert isinstance(cfg, TpuConfig)
        assert cfg.sharing.multi_tenancy.max_clients == 4
        cfg.normalize()
        cfg.validate()
        enc = encode_config(cfg)
        assert enc["kind"] == "TpuConfig"
        cfg2 = strict_decode(enc)
        assert cfg2.sharing.multi_tenancy.max_clients == 4

    def test_strict_rejects_unknown_fields(self):
        p = params("TpuConfig", sharing={"strategy": "TimeSlicing"},
                   bogus=True)
        with pytest.raises(DecodeError):
            strict_decode(p)
        # Nested unknown field too.
        p = params("TpuConfig",
                   sharing={"strategy": "TimeSlicing", "zzz": 1})
        with pytest.raises(DecodeError):
            strict_decode(p)

    def test_nonstrict_tolerates_unknown_fields(self):
        p = params("SubSliceConfig", sharing={"strategy": "TimeSlicing"},
                   futureField={"a": 1})
        cfg = nonstrict_decode(p)
        assert isinstance(cfg, SubSliceConfig)

    def test_wrong_api_version(self):
        with pytest.raises(DecodeError):
            strict_decode({"apiVersion": "v1", "kind": "TpuConfig"})

    def test_unknown_kind(self):
        with pytest.raises(DecodeError):
            strict_decode(params("GpuConfig"))

    def test_channel_decode(self):
        cfg = strict_decode(params(
            "ComputeDomainChannelConfig",
            domainID="uid-1", allocationMode="All"))
        assert cfg.domain_id == "uid-1"
        assert cfg.allocation_mode == "All"

    def test_type_error_surfaces_as_decode_error(self):
        with pytest.raises(DecodeError):
            strict_decode(params("TpuConfig", sharing=[1, 2]))


class TestComputeDomainCR:
    def test_roundtrip(self):
        cd = ComputeDomain(
            name="cd1", namespace="team-a", uid="u-1", num_nodes=4,
            topology="2x2x4",
            channel_resource_claim_template="cd1-channel",
            nodes=[ComputeDomainNode(name="n0", ip_address="10.0.0.1",
                                     clique_id="0", index=0,
                                     status="Ready")],
        )
        d = cd.to_dict()
        cd2 = ComputeDomain.from_dict(d)
        assert cd2 == cd

    def test_clique_roundtrip(self):
        cq = ComputeDomainClique(
            name="u-1.0", compute_domain_uid="u-1", clique_id="0",
            daemons=[ComputeDomainNode(name="n0", index=0)],
        )
        assert ComputeDomainClique.from_dict(cq.to_dict()) == cq

    def test_from_empty_dict(self):
        cd = ComputeDomain.from_dict({})
        assert cd.status == "NotReady"
        assert cd.nodes == []
