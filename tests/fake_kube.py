"""Test helpers: fabricate k8s objects (claims, etc.) as plain dicts."""

from __future__ import annotations

from k8s_dra_driver_gpu_tpu.kubeletplugin import DRIVER_NAME
from k8s_dra_driver_gpu_tpu.kubeletplugin.claim import ResourceClaim


def make_claim_dict(
    uid: str,
    devices: list[str],
    namespace: str = "default",
    name: str | None = None,
    configs: list[dict] | None = None,
    request: str = "tpu",
    driver: str = DRIVER_NAME,
) -> dict:
    """A resource.k8s.io/v1 ResourceClaim with an allocation for
    ``devices`` (canonical names) and optional opaque config entries:
    each config: {"parameters": {...}, "requests": [...], "source": ...}.
    """
    return {
        "metadata": {"uid": uid, "namespace": namespace, "name": name or uid},
        "status": {
            "allocation": {
                "devices": {
                    "results": [
                        {
                            "request": request,
                            "driver": driver,
                            "pool": "node",
                            "device": d,
                        }
                        for d in devices
                    ],
                    "config": [
                        {
                            "opaque": {
                                "driver": driver,
                                "parameters": c["parameters"],
                            },
                            "requests": c.get("requests", []),
                            "source": c.get("source", "FromClaim"),
                        }
                        for c in (configs or [])
                    ],
                }
            }
        },
    }


def make_claim(uid: str, devices: list[str], **kw) -> ResourceClaim:
    return ResourceClaim.from_dict(make_claim_dict(uid, devices, **kw))


class CountingKube:
    """KubeClient wrapper counting reads (get/list/server_version) and
    writes (create/update/patch/delete); watch hooks and everything
    else pass through, so informers keep working against the inner
    fake. The no-op steady-state and publish-diff regression tests
    gate on these counters."""

    def __init__(self, inner):
        self._inner = inner
        self.reads = 0
        self.writes = 0

    def get(self, *a, **kw):
        self.reads += 1
        return self._inner.get(*a, **kw)

    def list(self, *a, **kw):
        self.reads += 1
        return self._inner.list(*a, **kw)

    def server_version(self, *a, **kw):
        self.reads += 1
        return self._inner.server_version(*a, **kw)

    def create(self, *a, **kw):
        self.writes += 1
        return self._inner.create(*a, **kw)

    def update(self, *a, **kw):
        self.writes += 1
        return self._inner.update(*a, **kw)

    def patch(self, *a, **kw):
        self.writes += 1
        return self._inner.patch(*a, **kw)

    def delete(self, *a, **kw):
        self.writes += 1
        return self._inner.delete(*a, **kw)

    def __getattr__(self, item):
        return getattr(self._inner, item)


def opaque(kind: str, **fields) -> dict:
    return {
        "apiVersion": "resource.tpu.dra/v1beta1",
        "kind": kind,
        **fields,
    }


def wait_for_service(port: int, timeout: float = 30.0,
                     host: str = "127.0.0.1") -> str:
    """Poll a coordination service until it answers STATUS (interpreter
    startup on 1-core CI boxes takes seconds)."""
    import time

    from k8s_dra_driver_gpu_tpu.computedomain.daemon.rendezvous import query

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            return query(host, port, "STATUS")
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"coordination service on :{port} never came up")
