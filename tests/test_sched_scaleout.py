"""Scheduler scale-out tier (ISSUE 7): sharded multi-worker draining,
the optimistic fit/reserve/commit allocation protocol, batched
multi-claim allocation, snapshot signature caching under concurrent
invalidation, per-pool scheduling domains with leader election, and
deterministic interleaving coverage of two workers racing one node plus
a gang claim spanning both shards."""

import threading
import time
from contextlib import contextmanager

import pytest

from k8s_dra_driver_gpu_tpu.pkg.analysis.interleave import explore
from k8s_dra_driver_gpu_tpu.pkg.kubeclient import FakeKubeClient
from k8s_dra_driver_gpu_tpu.pkg.metrics import SchedulerMetrics
from k8s_dra_driver_gpu_tpu.pkg.schedcache import (
    AllocationState,
    ClusterView,
    DOMAIN_ANNOTATION,
    InventorySnapshot,
    NodeLockManager,
    SchedulingDomain,
)
from k8s_dra_driver_gpu_tpu.pkg.scheduler import (
    DraScheduler,
    run_leader_elected,
)
from k8s_dra_driver_gpu_tpu.pkg.sliceutil import publish_resource_slices

RES = ("resource.k8s.io", "v1")


def apply_class(kube, name="tpu.dra.dev"):
    kube.create(*RES, "deviceclasses", {
        "apiVersion": "resource.k8s.io/v1", "kind": "DeviceClass",
        "metadata": {"name": name},
        "spec": {"selectors": [{"cel": {
            "expression": f'device.driver == "{name}"'}}]},
    })


def node_slices(node, chips=4, driver="tpu.dra.dev"):
    return [{
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceSlice",
        "metadata": {"name": f"{node}-{driver}"},
        "spec": {"driver": driver, "nodeName": node,
                 "pool": {"name": node, "generation": 1,
                          "resourceSliceCount": 1},
                 "devices": [
                     {"name": f"chip-{j}", "attributes": {
                         "type": {"string": "tpu-chip"},
                         "index": {"int": j}}}
                     for j in range(chips)]},
    }]


def make_claim(kube, name, count=1, ns="default", cel=None,
               annotations=None):
    exactly = {"deviceClassName": "tpu.dra.dev"}
    if count != 1:
        exactly["count"] = count
    if cel:
        exactly["selectors"] = [{"cel": {"expression": cel}}]
    md = {"name": name, "namespace": ns, "uid": f"uid-{name}"}
    if annotations:
        md["annotations"] = dict(annotations)
    kube.create(*RES, "resourceclaims", {
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
        "metadata": md,
        "spec": {"devices": {"requests": [
            {"name": "tpu", "exactly": exactly}]}},
    }, namespace=ns)


def allocation(kube, name, ns="default"):
    return kube.get(*RES, "resourceclaims", name, ns).get(
        "status", {}).get("allocation")


def allocated_keys(kube):
    """claim name -> sorted device keys, plus the double-alloc audit."""
    out, seen, doubles = {}, set(), 0
    for claim in kube.objects("resource.k8s.io", "resourceclaims"):
        alloc = claim.get("status", {}).get("allocation")
        name = claim["metadata"]["name"]
        if not alloc:
            out[name] = None
            continue
        keys = sorted((r["driver"], r["pool"], r["device"])
                      for r in alloc["devices"]["results"])
        out[name] = keys
        for key in keys:
            if key in seen:
                doubles += 1
            seen.add(key)
    return out, doubles


class TestShardRouting:
    def test_control_keys_pin_to_worker_zero(self):
        fake = FakeKubeClient()
        sched = DraScheduler(fake, workers=4)
        for kind in ("full", "pending", "inventory", "daemonsets",
                     "jobs", "recovery", "pods-rescan"):
            assert sched._shard_of((kind,)) == 0
        # Claim/pod keys spread over the data workers (1..N-1), never
        # onto the control worker -- a claim flood cannot starve the
        # recovery/resync lane.
        shards = {sched._shard_of(("claim", "default", f"c-{i}"))
                  for i in range(64)}
        assert shards <= {1, 2, 3}
        assert len(shards) > 1
        # Stable per key, and pod/claim keys for one object co-shard.
        assert sched._shard_of(("claim", "ns", "x")) == \
            sched._shard_of(("claim", "ns", "x"))

    def test_single_worker_keeps_everything_on_worker_zero(self):
        sched = DraScheduler(FakeKubeClient(), workers=1)
        assert sched._shard_of(("claim", "default", "c")) == 0


class TestMultiWorkerAllocation:
    def test_racing_workers_never_double_allocate(self):
        """12 fungible claims against 8 chips under 4 workers: every
        chip allocated exactly once, exactly 8 claims converge."""
        fake = FakeKubeClient()
        apply_class(fake)
        for node in ("node-a", "node-b"):
            publish_resource_slices(fake, node_slices(node))
        sched = DraScheduler(fake, workers=4, batch_max=4,
                             sched_metrics=SchedulerMetrics())
        sched.start_event_driven()
        assert sched.drain(15.0)
        try:
            for i in range(12):
                make_claim(fake, f"c-{i}")
            assert sched.drain(30.0)
            # Retries for the 4 overflow claims settle via pending.
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                allocs, _ = allocated_keys(fake)
                if sum(1 for v in allocs.values() if v) == 8:
                    break
                time.sleep(0.02)
        finally:
            sched.stop()
        allocs, doubles = allocated_keys(fake)
        assert doubles == 0
        assert sum(1 for v in allocs.values() if v) == 8
        used = sorted(k for v in allocs.values() if v for k in v)
        assert len(used) == len(set(used)) == 8

    def test_multiworker_equivalent_to_single_worker_on_trace(self):
        """Acceptance: a recorded deterministic trace (pods born bound
        + chip-pinning selectors) produces IDENTICAL final allocations
        under workers=1 and workers=4."""

        def run(workers):
            fake = FakeKubeClient()
            apply_class(fake)
            for i in range(4):
                publish_resource_slices(fake, node_slices(f"node-{i}",
                                                          chips=2))
            sched = DraScheduler(fake, workers=workers, batch_max=4)
            sched.start_event_driven()
            assert sched.drain(15.0)
            try:
                for idx in range(8):
                    name = f"c-{idx}"
                    fake.create("", "v1", "pods", {
                        "apiVersion": "v1", "kind": "Pod",
                        "metadata": {"name": f"{name}-pod",
                                     "namespace": "default"},
                        "spec": {"containers": [{"name": "c"}],
                                 "nodeName": f"node-{idx % 4}",
                                 "resourceClaims": [{
                                     "name": "tpu",
                                     "resourceClaimName": name}]},
                    }, namespace="default")
                    make_claim(fake, name, cel=(
                        'device.attributes["tpu.dra.dev"].index == '
                        f'{idx // 4}'))
                assert sched.drain(30.0)
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    allocs, _ = allocated_keys(fake)
                    if all(allocs.get(f"c-{i}") for i in range(8)):
                        break
                    time.sleep(0.02)
            finally:
                sched.stop()
            return allocated_keys(fake)

        single, d1 = run(1)
        multi, d4 = run(4)
        assert d1 == d4 == 0
        assert single == multi
        assert all(single[f"c-{i}"] for i in range(8))

    def test_rebuild_during_patch_window_keeps_reservation(self):
        """A state rebuild (safety resync) racing the patch window of
        an in-flight commit must still see the reserved devices: the
        commit-log entry lands BEFORE the patch, so the replay carries
        the reservation into the fresh AllocationState instead of
        resurrecting the device as free (double-allocation window)."""
        fake = FakeKubeClient()
        apply_class(fake)
        publish_resource_slices(fake, node_slices("node-a", chips=1))
        sched = DraScheduler(fake)
        sched.start_event_driven()
        assert sched.drain(15.0)
        real_patch = fake.patch
        raced: dict = {}

        def racing_patch(group, version, resource, name, patch,
                         namespace=None, **kw):
            if resource == "resourceclaims" and \
                    (patch.get("status") or {}).get("allocation") and \
                    "alloc2" not in raced:
                # The resync fires exactly inside the patch window; the
                # claim cache cannot contain this allocation yet.
                _, raced["alloc2"] = sched._rebuild_alloc_state()
            return real_patch(group, version, resource, name, patch,
                              namespace=namespace, **kw)

        fake.patch = racing_patch
        try:
            make_claim(fake, "c1")
            assert sched.drain(15.0)
            assert allocation(fake, "c1")
        finally:
            sched.stop()
            fake.patch = real_patch
        key = ("tpu.dra.dev", "node-a", "chip-0")
        assert key in raced["alloc2"].allocated, \
            "in-flight reservation lost across a state rebuild"

    def test_commit_reserves_against_live_state_after_swap(self):
        """A commit whose caller captured a since-superseded
        AllocationState must reserve against the LIVE state: reserving
        only into the dead capture would leave the live state showing
        the devices free until the claim's watch event arrives."""
        fake = FakeKubeClient()
        apply_class(fake)
        publish_resource_slices(fake, node_slices("node-a", chips=1))
        sched = DraScheduler(fake)  # direct mode: no events to mask it
        make_claim(fake, "c1")
        claim = fake.get(*RES, "resourceclaims", "c1", "default")
        snap, alloc1 = sched._ensure_alloc_state()
        classes = sched._device_classes()
        _, alloc2 = sched._rebuild_alloc_state()  # the swap
        assert alloc2 is not alloc1
        assert sched._allocate_one(claim, snap, alloc1,
                                   classes) == "committed"
        key = ("tpu.dra.dev", "node-a", "chip-0")
        assert key in alloc2.allocated, \
            "reservation landed only in the superseded state"
        assert allocation(fake, "c1")

    def test_batch_setup_failure_releases_taken_keys(self):
        """If the batched path's shared setup dies after take_ready,
        every taken key must be finished (re-enqueued with its error)
        -- otherwise those claims wedge as running forever."""
        from k8s_dra_driver_gpu_tpu.pkg.kubeclient import KubeError

        fake = FakeKubeClient()
        apply_class(fake)
        publish_resource_slices(fake, node_slices("node-a", chips=8))
        sched = DraScheduler(fake, workers=1, batch_max=8)
        sched.start_event_driven()
        assert sched.drain(15.0)
        orig = sched._device_classes
        state = {"failed": False}

        def flaky():
            if not state["failed"]:
                state["failed"] = True
                raise KubeError(503, "transient")
            return orig()

        sched._device_classes = flaky
        try:
            block = threading.Event()
            started = threading.Event()
            sched._queue.enqueue(
                ("block",), lambda k: (started.set(), block.wait(5.0)))
            assert started.wait(5.0)
            for i in range(5):
                make_claim(fake, f"f-{i}")
            time.sleep(0.1)
            block.set()
            assert sched.drain(30.0)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if all(allocation(fake, f"f-{i}") for i in range(5)):
                    break
                time.sleep(0.02)
            assert all(allocation(fake, f"f-{i}") for i in range(5)), \
                "batch-taken keys wedged after setup failure"
        finally:
            sched.stop()

    def test_commit_conflict_metric_counts(self):
        """A planned allocation whose devices vanish between fit and
        reserve reports a conflict and re-fits."""
        fake = FakeKubeClient()
        apply_class(fake)
        publish_resource_slices(fake, node_slices("node-a", chips=2))
        sm = SchedulerMetrics()
        sched = DraScheduler(fake, sched_metrics=sm)
        snap, alloc = sched._ensure_alloc_state()
        classes = sched._device_classes()
        make_claim(fake, "victim")
        claim = fake.get(*RES, "resourceclaims", "victim", "default")

        # Steal chip-0 between the fit and the reserve by wrapping
        # try_commit's first invocation.
        orig = alloc.try_commit
        stolen = {"done": False}

        def stealing(claim_like):
            if not stolen["done"]:
                stolen["done"] = True
                orig({"metadata": {"uid": "thief", "name": "thief",
                                   "namespace": "default"},
                      "status": {"allocation": {"devices": {"results": [
                          {"driver": "tpu.dra.dev", "pool": "node-a",
                           "device": claim_like["status"]["allocation"][
                               "devices"]["results"][0]["device"]},
                      ]}}}})
            return orig(claim_like)

        alloc.try_commit = stealing
        assert sched._allocate_one(claim, snap, alloc,
                                   classes) == "committed"
        got = allocation(fake, "victim")
        assert got is not None
        # The re-fit picked the surviving chip, not the stolen one.
        thief_dev = next(iter(alloc._claims["thief"]))[2]
        assert got["devices"]["results"][0]["device"] != thief_dev
        text = sm.commit_conflicts.collect()[0].samples[0].value
        assert text >= 1


class TestBatchedAllocation:
    def test_burst_drains_in_batches_and_all_allocate(self):
        fake = FakeKubeClient()
        apply_class(fake)
        for node in ("node-a", "node-b", "node-c"):
            publish_resource_slices(fake, node_slices(node))
        sched = DraScheduler(fake, workers=1, batch_max=8,
                             sched_metrics=SchedulerMetrics())
        sched.start_event_driven()
        assert sched.drain(15.0)
        batches = []
        orig = sched._queue.take_ready

        def spy(pred, limit):
            got = orig(pred, limit)
            if got:
                batches.append(len(got))
            return got

        sched._queue.take_ready = spy
        try:
            # Park the worker so the burst is all due at once.
            block = threading.Event()
            started = threading.Event()
            sched._queue.enqueue(
                ("block",), lambda k: (started.set(), block.wait(5.0)))
            assert started.wait(5.0)
            for i in range(10):
                make_claim(fake, f"b-{i}")
            time.sleep(0.1)  # let the claim events enqueue
            block.set()
            assert sched.drain(30.0)
            assert all(allocation(fake, f"b-{i}") for i in range(10))
        finally:
            sched.stop()
        # At least one multi-claim batch formed (amortized snapshot).
        assert batches and max(batches) >= 2
        _, doubles = allocated_keys(fake)
        assert doubles == 0


class TestSnapshotRace:
    def test_stale_listing_never_installed_over_concurrent_bump(self):
        """Satellite: an event-thread generation bump racing a
        worker's snapshot() must never serve a stale-generation
        snapshot to a commit. The stale listing (taken before the
        bump) is detected via the slice generation and re-listed."""
        fake = FakeKubeClient()
        publish_resource_slices(fake, node_slices("node-a", chips=4))
        view = ClusterView(fake)
        stale = [dict(s) for s in fake.list(*RES, "resourceslices")]
        # The inventory grows (generation bump) -- this is the state
        # every commit from now on must see.
        publish_resource_slices(fake, node_slices("node-a", chips=6))

        orig_list = fake.list
        raced = {"done": False}

        def racy_list(group, version, resource, namespace=None, **kw):
            if resource == "resourceslices" and not raced["done"]:
                raced["done"] = True
                # The "event" lands AFTER our listing was taken: bump
                # the generation and hand back the stale world.
                view.invalidate_snapshot()
                return stale
            return orig_list(group, version, resource,
                             namespace=namespace, **kw)

        fake.list = racy_list
        snap = view.snapshot()
        names = {c.name for c in snap.candidates}
        assert "chip-5" in names, \
            "stale-generation snapshot served to a commit"
        assert raced["done"]

    def test_snapshot_build_time_exported(self):
        fake = FakeKubeClient()
        apply_class(fake)
        publish_resource_slices(fake, node_slices("node-a"))
        sm = SchedulerMetrics()
        sched = DraScheduler(fake, sched_metrics=sm)
        sched.sync_once()
        from prometheus_client import generate_latest

        text = generate_latest(sm.registry).decode()
        assert "tpu_dra_sched_snapshot_build_seconds_count 1" in text

    def test_concurrent_snapshot_readers_one_build(self):
        fake = FakeKubeClient()
        publish_resource_slices(fake, node_slices("node-a"))
        builds = []
        view = ClusterView(fake,
                           on_snapshot_build=lambda dt: builds.append(dt))
        snaps = []
        threads = [threading.Thread(
            target=lambda: snaps.append(view.snapshot()))
            for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5.0)
        assert len({id(s) for s in snaps}) == 1
        assert len(builds) == 1


class TestSchedulingDomains:
    def test_domains_partition_pools_and_claims(self):
        fake = FakeKubeClient()
        apply_class(fake)
        publish_resource_slices(fake, node_slices("node-a"))
        publish_resource_slices(fake, node_slices("node-b"))
        dom_a = SchedulingDomain("a", pools=["node-a"], default=True)
        dom_b = SchedulingDomain("b", pools=["node-b"])
        sched_a = DraScheduler(fake, domain=dom_a)
        sched_b = DraScheduler(fake, domain=dom_b)
        make_claim(fake, "c-plain")  # unannotated -> default domain a
        make_claim(fake, "c-b", annotations={DOMAIN_ANNOTATION: "b"})
        # b syncs first: it must not touch the default-domain claim.
        sched_b.sync_once()
        assert allocation(fake, "c-plain") is None
        assert allocation(fake, "c-b")["devices"]["results"][0][
            "pool"] == "node-b"
        sched_a.sync_once()
        got = allocation(fake, "c-plain")
        assert got["devices"]["results"][0]["pool"] == "node-a"

    def test_domain_snapshot_restricted_to_own_pools(self):
        fake = FakeKubeClient()
        publish_resource_slices(fake, node_slices("node-a"))
        publish_resource_slices(fake, node_slices("node-b"))
        sched = DraScheduler(
            fake, domain=SchedulingDomain("b", pools=["node-b"]))
        snap = sched.view.snapshot()
        assert set(snap.by_node) == {"node-b"}

    def test_domain_pool_globs(self):
        dom = SchedulingDomain("edge", pools=["edge-*"])
        assert dom.owns_pool("edge-7", "edge-7")
        assert not dom.owns_pool("core-1", "core-1")

    def test_generated_claim_inherits_pod_domain(self):
        fake = FakeKubeClient()
        apply_class(fake)
        publish_resource_slices(fake, node_slices("node-b"))
        fake.create(*RES, "resourceclaimtemplates", {
            "apiVersion": "resource.k8s.io/v1",
            "kind": "ResourceClaimTemplate",
            "metadata": {"name": "tpl", "namespace": "default"},
            "spec": {"spec": {"devices": {"requests": [
                {"name": "tpu",
                 "exactly": {"deviceClassName": "tpu.dra.dev"}}]}}},
        }, namespace="default")
        fake.create("", "v1", "pods", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "worker", "namespace": "default",
                         "annotations": {DOMAIN_ANNOTATION: "b"}},
            "spec": {"containers": [{"name": "c"}],
                     "resourceClaims": [{
                         "name": "tpu",
                         "resourceClaimTemplateName": "tpl"}]},
        }, namespace="default")
        sched = DraScheduler(
            fake, domain=SchedulingDomain("b", pools=["node-b"]))
        sched.sync_once()
        sched.sync_once()
        pod = fake.get("", "v1", "pods", "worker", "default")
        generated = pod["status"]["resourceClaimStatuses"][0][
            "resourceClaimName"]
        claim = fake.get(*RES, "resourceclaims", generated, "default")
        assert claim["metadata"]["annotations"][DOMAIN_ANNOTATION] == "b"
        assert claim["status"]["allocation"]

    def test_leader_election_gates_domain_scheduler(self):
        """Two instances of one domain: the standby idles (no queue,
        no writes) until the leader steps down, then takes over."""
        fake = FakeKubeClient()
        apply_class(fake)
        publish_resource_slices(fake, node_slices("node-a"))
        lease_kw = dict(lease_duration=1.0, renew_period=0.1,
                        retry_period=0.05)
        dom = SchedulingDomain("a", pools=["node-a"], default=True)
        sched1 = DraScheduler(fake, domain=dom)
        sched2 = DraScheduler(fake, domain=dom)
        stop1, stop2 = threading.Event(), threading.Event()
        t1 = threading.Thread(
            target=run_leader_elected,
            args=(sched1,), kwargs=dict(identity="i1", stop=stop1,
                                        **lease_kw), daemon=True)
        t1.start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and sched1._queue is None:
            time.sleep(0.01)
        assert sched1._queue is not None, "leader never started"
        t2 = threading.Thread(
            target=run_leader_elected,
            args=(sched2,), kwargs=dict(identity="i2", stop=stop2,
                                        **lease_kw), daemon=True)
        t2.start()
        make_claim(fake, "c1")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not allocation(fake, "c1"):
            time.sleep(0.02)
        assert allocation(fake, "c1")
        assert sched2._queue is None, "standby ran while leader held"
        # Leader steps down; the standby must take over the domain.
        stop1.set()
        t1.join(10.0)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and sched2._queue is None:
            time.sleep(0.02)
        assert sched2._queue is not None, "standby never took over"
        make_claim(fake, "c2")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not allocation(fake, "c2"):
            time.sleep(0.02)
        assert allocation(fake, "c2")
        stop2.set()
        t2.join(10.0)


class TestDomainExhausted:
    """A domain-pinned claim that cannot fit inside its scheduling
    domain must surface the wedge (condition + deduped Warning Event +
    metric) instead of sitting silently Pending."""

    def _exhausted_setup(self):
        fake = FakeKubeClient()
        apply_class(fake)
        publish_resource_slices(fake, node_slices("node-b", chips=1))
        sm = SchedulerMetrics()
        sched = DraScheduler(
            fake, domain=SchedulingDomain("b", pools=["node-b"]),
            sched_metrics=sm)
        make_claim(fake, "fill", annotations={DOMAIN_ANNOTATION: "b"})
        sched.sync_once()
        assert allocation(fake, "fill") is not None
        make_claim(fake, "wedged",
                   annotations={DOMAIN_ANNOTATION: "b"})
        sched.sync_once()
        return fake, sched, sm

    def test_condition_event_and_metric(self):
        fake, sched, sm = self._exhausted_setup()
        claim = fake.get(*RES, "resourceclaims", "wedged", "default")
        conds = claim["status"]["conditions"]
        assert any(c["type"] == "DomainExhausted"
                   and c["status"] == "True" for c in conds)
        events = [e for e in fake.objects("", "events")
                  if e.get("reason") == "DomainExhausted"]
        assert len(events) == 1
        assert events[0]["type"] == "Warning"
        assert events[0]["involvedObject"]["name"] == "wedged"
        assert sm.domain_exhausted.labels("b")._value.get() >= 1

    def test_condition_and_event_deduped_across_passes(self):
        fake, sched, sm = self._exhausted_setup()
        for _ in range(3):
            sched.sync_once()
        claim = fake.get(*RES, "resourceclaims", "wedged", "default")
        conds = [c for c in claim["status"]["conditions"]
                 if c["type"] == "DomainExhausted"]
        assert len(conds) == 1
        events = [e for e in fake.objects("", "events")
                  if e.get("reason") == "DomainExhausted"]
        assert len(events) == 1
        # The metric keeps counting attempts even though the claim
        # surface stays quiet.
        assert sm.domain_exhausted.labels("b")._value.get() >= 4

    def test_condition_clears_when_capacity_frees(self):
        fake, sched, sm = self._exhausted_setup()
        fake.delete(*RES, "resourceclaims", "fill",
                    namespace="default")
        sched.sync_once()
        claim = fake.get(*RES, "resourceclaims", "wedged", "default")
        assert claim["status"]["allocation"]
        conds = [c for c in claim["status"]["conditions"]
                 if c["type"] == "DomainExhausted"]
        assert len(conds) == 1 and conds[0]["status"] == "False"
        assert conds[0]["reason"] == "Allocated"

    def test_unpinned_claim_not_flagged(self):
        """Unfit claims in the default domain (no annotation) are NOT
        a domain wedge -- no condition, no event."""
        fake = FakeKubeClient()
        apply_class(fake)
        publish_resource_slices(fake, node_slices("node-a", chips=1))
        sched = DraScheduler(
            fake,
            domain=SchedulingDomain("a", pools=["node-a"],
                                    default=True))
        make_claim(fake, "fill")
        sched.sync_once()
        make_claim(fake, "overflow")
        sched.sync_once()
        claim = fake.get(*RES, "resourceclaims", "overflow", "default")
        assert not (claim.get("status") or {}).get("conditions")
        assert not [e for e in fake.objects("", "events")
                    if e.get("reason") == "DomainExhausted"]


class TestInterleavedAllocation:
    """Deterministic interleaving coverage (pkg/analysis/interleave)
    of the sharded allocation protocol: two workers racing one node,
    and a CD-window gang spanning both shards racing a single-node
    claim. No deadlock, no double allocation, over DFS schedules."""

    @pytest.fixture()
    def instrumented(self):
        current = {"sched": None}
        orig_hold = NodeLockManager.hold
        # The commit choice point sits at _commit_allocation entry
        # (BEFORE the registry lock): yielding while holding a real
        # lock would stall the cooperative explorer.
        orig_commit = DraScheduler._commit_allocation
        orig_patch = FakeKubeClient.patch

        @contextmanager
        def vhold(self, nodes):
            vs = current["sched"]
            if vs is None or vs._current() is None:
                with orig_hold(self, nodes):
                    yield
                return
            ids = sorted(set(nodes))
            for n in ids:
                vs.lock_acquire(("node", n), reentrant_error=False)
            try:
                yield
            finally:
                for n in reversed(ids):
                    vs.lock_release(("node", n))

        def vcommit(self, claim, alloc_obj, snap, alloc):
            vs = current["sched"]
            if vs is not None:
                vs.yield_point("commit")
            return orig_commit(self, claim, alloc_obj, snap, alloc)

        def vpatch(self, *a, **kw):
            vs = current["sched"]
            if vs is not None:
                vs.yield_point("kube.patch")
            return orig_patch(self, *a, **kw)

        NodeLockManager.hold = vhold
        DraScheduler._commit_allocation = vcommit
        FakeKubeClient.patch = vpatch
        try:
            yield current
        finally:
            NodeLockManager.hold = orig_hold
            DraScheduler._commit_allocation = orig_commit
            FakeKubeClient.patch = orig_patch

    def test_two_workers_racing_one_node(self, instrumented):
        """One free chip, two claims, two workers: exactly one claim
        wins, the other pends; never a double allocation or deadlock."""

        def build(vsched):
            instrumented["sched"] = vsched
            fake = FakeKubeClient.__new__(FakeKubeClient)
            FakeKubeClient.__init__(fake)
            apply_class(fake)
            publish_resource_slices(fake, node_slices("node-a", chips=1))
            make_claim(fake, "r1")
            make_claim(fake, "r2")
            dra = DraScheduler(fake)
            dra._ensure_alloc_state()
            vsched.fake = fake

            def worker(name):
                def run():
                    dra._sync_claim_key("default", name)
                return run

            vsched.spawn(worker("r1"), name="w1")
            vsched.spawn(worker("r2"), name="w2")

        def invariant(vsched):
            allocs, doubles = allocated_keys(vsched.fake)
            assert doubles == 0
            winners = [n for n, v in allocs.items() if v]
            assert len(winners) == 1, f"expected one winner: {allocs}"

        result = explore(build, invariant, max_schedules=300)
        assert result.ok, "\n".join(str(f) for f in result.failures)
        assert result.schedules_run > 1

    def test_gang_window_spanning_shards_vs_single_node(self,
                                                        instrumented):
        """A CD-window gang claim whose multi-node lock set spans
        node-a+node-b races a single-node claim on node-b: sorted
        lock-set acquisition means no schedule deadlocks, and every
        schedule converges with unique devices."""

        def build(vsched):
            instrumented["sched"] = vsched
            fake = FakeKubeClient.__new__(FakeKubeClient)
            FakeKubeClient.__init__(fake)
            apply_class(fake)
            publish_resource_slices(fake, node_slices("node-a", chips=1))
            publish_resource_slices(fake, node_slices("node-b", chips=2))
            make_claim(fake, "gang-1")
            make_claim(fake, "solo")
            dra = DraScheduler(fake)

            orig_window = DraScheduler._preferred_gang_nodes

            def windowed(self, claim):
                if claim["metadata"]["name"].startswith("gang"):
                    return ["node-a", "node-b"]
                return orig_window(self, claim)

            dra._preferred_gang_nodes = windowed.__get__(dra)
            dra._ensure_alloc_state()
            vsched.fake = fake

            def worker(name):
                def run():
                    dra._sync_claim_key("default", name)
                return run

            vsched.spawn(worker("gang-1"), name="gang")
            vsched.spawn(worker("solo"), name="solo")

        def invariant(vsched):
            allocs, doubles = allocated_keys(vsched.fake)
            assert doubles == 0
            # Capacity 3, demand 2: both always converge.
            assert allocs["gang-1"] and allocs["solo"], allocs

        result = explore(build, invariant, max_schedules=300)
        assert result.ok, "\n".join(str(f) for f in result.failures)


class TestWorkqueueMetricsExposition:
    def test_queue_and_snapshot_metrics_on_scheduler_registry(self):
        from prometheus_client import generate_latest

        fake = FakeKubeClient()
        apply_class(fake)
        publish_resource_slices(fake, node_slices("node-a"))
        sm = SchedulerMetrics()
        sched = DraScheduler(fake, workers=2, sched_metrics=sm)
        sched.start_event_driven()
        assert sched.drain(15.0)
        make_claim(fake, "c1")
        assert sched.drain(15.0)
        sched.stop()
        text = generate_latest(sm.registry).decode()
        assert 'tpu_dra_workqueue_depth{shard=' in text
        assert "tpu_dra_workqueue_wait_seconds_count" in text
        assert "tpu_dra_workqueue_retries_total" in text
        assert "tpu_dra_workqueue_hot_backoff_total" in text
        assert "tpu_dra_sched_snapshot_build_seconds" in text
        assert "tpu_dra_sched_commit_conflicts_total" in text


class TestAllocationStateConcurrency:
    def test_try_commit_rejects_taken_device(self):
        snap = InventorySnapshot(node_slices("node-a", chips=2))
        alloc = AllocationState(snap)
        taken = {
            "metadata": {"uid": "u1", "name": "c1",
                         "namespace": "default"},
            "status": {"allocation": {"devices": {"results": [
                {"driver": "tpu.dra.dev", "pool": "node-a",
                 "device": "chip-0"}]}}},
        }
        assert alloc.try_commit(taken)
        rival = {
            "metadata": {"uid": "u2", "name": "c2",
                         "namespace": "default"},
            "status": {"allocation": {"devices": {"results": [
                {"driver": "tpu.dra.dev", "pool": "node-a",
                 "device": "chip-0"}]}}},
        }
        assert not alloc.try_commit(rival)
        # Idempotent replay of the winner's own reservation.
        assert alloc.try_commit(taken)
        assert alloc.node_load == {"node-a": 1}

    def test_node_load_maintained_incrementally(self):
        snap = InventorySnapshot(node_slices("node-a", chips=4))
        alloc = AllocationState(snap)
        claims = []
        for i in range(3):
            c = {
                "metadata": {"uid": f"u{i}", "name": f"c{i}",
                             "namespace": "default"},
                "status": {"allocation": {"devices": {"results": [
                    {"driver": "tpu.dra.dev", "pool": "node-a",
                     "device": f"chip-{i}"}]}}},
            }
            claims.append(c)
            alloc.observe(c)
        assert alloc.load_view() == {"node-a": 3}
        alloc.forget(claims[0])
        assert alloc.load_view() == {"node-a": 2}

    def test_concurrent_observe_forget_stress(self):
        snap = InventorySnapshot(node_slices("node-a", chips=8))
        alloc = AllocationState(snap)
        errs = []

        def churn(base):
            try:
                for i in range(200):
                    c = {
                        "metadata": {"uid": f"{base}-{i % 4}",
                                     "name": f"{base}-{i % 4}",
                                     "namespace": "default"},
                        "status": {"allocation": {"devices": {
                            "results": [{
                                "driver": "tpu.dra.dev",
                                "pool": "node-a",
                                "device": f"chip-{i % 8}"}]}}},
                    }
                    alloc.observe(c)
                    alloc.load_view()
                    alloc.ledger_snapshot()
                    alloc.forget(c)
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        threads = [threading.Thread(target=churn, args=(f"t{j}",))
                   for j in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(20.0)
        assert not errs
        assert alloc.load_view() == {}
        assert not alloc.allocated
