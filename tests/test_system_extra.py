"""System-tier breadth: logging contract, sustained churn, and
chart-driven up/downgrade over a live checkpoint.

Reference analogs: tests/bats/test_cd_logging.bats (verbosity levels
emit/omit the documented lines), test_gpu_stress.bats (shared claims
churned across many pods, repeated), test_gpu_up_downgrade.bats and
test_cd_up_downgrade.bats (old release <-> new release over live
state). All drive the REAL binaries as subprocesses against the fake
apiserver + fake kubelet.
"""

import os
import signal
import statistics
import subprocess
import sys
import threading
import time

import pytest
import yaml

from k8s_dra_driver_gpu_tpu.pkg.fakeapiserver import FakeApiServer
from k8s_dra_driver_gpu_tpu.pkg.kubeclient import KubeClient
from tests.fake_kube import make_claim_dict
from tests.fake_kubelet import FakeKubelet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ, "PYTHONPATH": REPO}
DRIVER = "tpu.dra.dev"

# Scale knobs (CI can raise them; defaults keep the suite quick on the
# 1-core dev box).
CHURN_SECONDS = float(os.environ.get("TPU_DRA_CHURN_SECONDS", "15"))
CHURN_WORKERS = int(os.environ.get("TPU_DRA_CHURN_WORKERS", "4"))


def start_plugin(tmp_path, api_url, extra_env=None, name="plugin"):
    log_path = tmp_path / f"{name}.log"
    log = open(log_path, "w", encoding="utf-8")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "k8s_dra_driver_gpu_tpu.kubeletplugin.main"],
        env={**ENV,
             "KUBE_API": api_url,
             "NODE_NAME": "node-sys",
             "TPULIB_MOCK_TOPOLOGY": "v5e-4",
             "STATE_ROOT": str(tmp_path / "state"),
             "CDI_ROOT": str(tmp_path / "cdi"),
             "PLUGIN_DIR": str(tmp_path / "plugin"),
             "REGISTRY_DIR": str(tmp_path / "registry"),
             **(extra_env or {})},
        stdout=log, stderr=subprocess.STDOUT,
    )
    return proc, log, log_path


def stop(proc, log):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
    log.close()


class TestLoggingContract:
    """The documented verbosity contract (docs/install.md, enforced
    here like tests/bats/test_cd_logging.bats): 0 = errors + the
    always-on startup config dump; 4 = claim lifecycle; 6 = t_prep_*
    segment timings."""

    def _drive_one_claim(self, tmp_path, verbosity):
        api = FakeApiServer().start()
        proc, log, log_path = start_plugin(
            tmp_path, api.url, {"V": str(verbosity)},
            name=f"plugin-v{verbosity}")
        try:
            kubelet = FakeKubelet(str(tmp_path / "registry"))
            kubelet.wait_for_plugin(DRIVER, timeout=60)
            kube = KubeClient(host=api.url)
            uid = f"log-claim-v{verbosity}"
            kube.create("resource.k8s.io", "v1", "resourceclaims",
                        make_claim_dict(uid, ["chip-0"], namespace="ns1",
                                        name=uid), namespace="ns1")
            resp = kubelet.prepare(DRIVER, [
                {"uid": uid, "namespace": "ns1", "name": uid}])
            assert resp.claims[uid].error == ""
            kubelet.unprepare(DRIVER, [uid])
        finally:
            stop(proc, log)
            api.stop()
        return log_path.read_text()

    def test_verbosity_0_errors_plus_startup_config(self, tmp_path):
        text = self._drive_one_claim(tmp_path, 0)
        # Startup banner + config dump survive verbosity 0 (the
        # reference asserts config detail in level-0 logs).
        assert "tpu-kubelet-plugin" in text and "starting" in text
        assert "config node_name='node-sys'" in text
        assert "config publication_mode=" in text
        # Lifecycle and timing detail are gated off.
        assert "prepared claim" not in text
        assert "t_prep_" not in text

    def test_verbosity_4_claim_lifecycle(self, tmp_path):
        text = self._drive_one_claim(tmp_path, 4)
        assert "prepared claim log-claim-v4" in text
        assert "t_prep_" not in text

    def test_verbosity_6_prep_segments(self, tmp_path):
        text = self._drive_one_claim(tmp_path, 6)
        assert "prepared claim log-claim-v6" in text
        assert "t_prep_devices" in text
        assert "t_checkpoint_write" in text

    def test_webhook_startup_config_at_verbosity_0(self, tmp_path):
        out = subprocess.run(
            [sys.executable, "-c",
             "from k8s_dra_driver_gpu_tpu.webhook.main import main\n"
             "import threading, os, signal\n"
             "threading.Timer(1.0, lambda: os.kill(os.getpid(), "
             "signal.SIGINT)).start()\n"
             "main(['--port', '0', '-v', '0'])"],
            env=ENV, cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        text = out.stdout + out.stderr
        assert "tpu-dra-webhook" in text and "starting" in text
        assert "config port=0" in text

    def test_cd_controller_startup_config_at_verbosity_0(self, tmp_path):
        out = subprocess.run(
            [sys.executable, "-c",
             "from k8s_dra_driver_gpu_tpu.computedomain.controller.main "
             "import run\n"
             "import threading, os, signal\n"
             "threading.Timer(1.0, lambda: os.kill(os.getpid(), "
             "signal.SIGTERM)).start()\n"
             "run(['--standalone', '-v', '0'])"],
            env=ENV, cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        text = out.stdout + out.stderr
        assert "compute-domain-controller" in text and "starting" in text
        assert "config max_nodes_per_domain=64" in text


class TestSustainedChurn:
    """Overlapping prepare/unprepare churn against the live binary
    (test_gpu_stress.bats analog): per-op latency stays bounded and no
    state leaks once the churn drains."""

    def test_churn_bounded_latency_no_leaks(self, tmp_path):
        api = FakeApiServer().start()
        proc, log, log_path = start_plugin(
            tmp_path, api.url,
            {"FEATURE_GATES": "TimeSlicingSettings=true"},
            name="plugin-churn")
        try:
            kubelet = FakeKubelet(str(tmp_path / "registry"))
            kubelet.wait_for_plugin(DRIVER, timeout=60)
            kube = KubeClient(host=api.url)

            # A shared time-sliced claim churned by every worker plus a
            # per-worker exclusive-chip claim: exercises the flock, the
            # checkpoint RMW, per-chip policy holder counting, and the
            # overlap validator concurrently.
            shared_uid = "churn-shared"
            kube.create(
                "resource.k8s.io", "v1", "resourceclaims",
                make_claim_dict(
                    shared_uid, ["chip-0"], namespace="ns1",
                    name=shared_uid,
                    configs=[{"parameters": {
                        "apiVersion": "resource.tpu.dra/v1beta1",
                        "kind": "TpuConfig",
                        "sharing": {
                            "strategy": "TimeSlicing",
                            "timeSlicing": {"interval": "Short"},
                        },
                    }}]),
                namespace="ns1")

            latencies = []
            errors = []
            lat_lock = threading.Lock()
            deadline = time.monotonic() + CHURN_SECONDS

            def worker(wid):
                # Workers 0-2 churn exclusive whole-chip claims on
                # their own chip (1..3); further workers churn the
                # shared time-sliced claim on chip-0 (whole-chip and
                # shared holders on the SAME chip correctly conflict,
                # so the pools stay disjoint).
                exclusive = wid < 3
                chip = f"chip-{wid + 1}" if exclusive else "chip-0"
                seq = 0
                while time.monotonic() < deadline:
                    seq += 1
                    try:
                        if not exclusive:
                            t0 = time.monotonic()
                            rs = kubelet.prepare(DRIVER, [
                                {"uid": shared_uid, "namespace": "ns1",
                                 "name": shared_uid}])
                            if rs.claims[shared_uid].error:
                                errors.append(rs.claims[shared_uid].error)
                            kubelet.unprepare(DRIVER, [shared_uid])
                            with lat_lock:
                                latencies.append(time.monotonic() - t0)
                            continue
                        uid = f"churn-{wid}-{seq}"
                        kube.create(
                            "resource.k8s.io", "v1", "resourceclaims",
                            make_claim_dict(uid, [chip], namespace="ns1",
                                            name=uid), namespace="ns1")
                        t0 = time.monotonic()
                        r = kubelet.prepare(DRIVER, [
                            {"uid": uid, "namespace": "ns1", "name": uid}])
                        if r.claims[uid].error:
                            errors.append(r.claims[uid].error)
                        u = kubelet.unprepare(DRIVER, [uid])
                        if u.claims[uid].error:
                            errors.append(u.claims[uid].error)
                        with lat_lock:
                            latencies.append(time.monotonic() - t0)
                        kube.delete("resource.k8s.io", "v1",
                                    "resourceclaims", uid, namespace="ns1")
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))
                        return

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(CHURN_WORKERS)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=CHURN_SECONDS + 120)
            assert not errors, errors[:5]
            assert len(latencies) >= CHURN_WORKERS * 3, (
                f"churn made no progress: {len(latencies)} ops")
            latencies.sort()
            p99 = latencies[int(len(latencies) * 0.99) - 1]
            # Generous bound: catches pathological serialization (the
            # reference's regime is 10s flock timeouts under load).
            assert p99 < 5.0, f"p99 {p99:.2f}s over {len(latencies)} ops"

            # Drain check: nothing leaked.
            cdi = tmp_path / "cdi"
            leftover = [f for f in os.listdir(cdi)
                        if f.endswith(".json")] if cdi.is_dir() else []
            assert not leftover, f"leaked CDI specs: {leftover}"
            # The plugin is still fully serviceable after the churn.
            kube.create("resource.k8s.io", "v1", "resourceclaims",
                        make_claim_dict("post", ["chip-1"],
                                        namespace="ns1", name="post"),
                        namespace="ns1")
            r = kubelet.prepare(DRIVER, [
                {"uid": "post", "namespace": "ns1", "name": "post"}])
            assert r.claims["post"].error == ""
            assert kubelet.unprepare(
                DRIVER, ["post"]).claims["post"].error == ""
        finally:
            stop(proc, log)
            api.stop()


class TestChartDrivenUpDowngrade:
    """Upgrade rollout over a LIVE checkpoint, configured the way the
    chart actually configures the DaemonSet (env rendered from values)
    -- the test_gpu_up_downgrade.bats role: old config prepares, new
    config must adopt the state, republish, and unprepare cleanly."""

    def _chart_env(self, overrides):
        from k8s_dra_driver_gpu_tpu.pkg.chartrender import render_chart

        rendered = render_chart(
            os.path.join(REPO, "deployments", "helm", "tpu-dra-driver"),
            overrides=overrides)
        for text in rendered.values():
            for d in yaml.safe_load_all(text):
                if (d and d.get("kind") == "DaemonSet"
                        and "kubelet" in d["metadata"]["name"]):
                    c = d["spec"]["template"]["spec"]["containers"][0]
                    return {e["name"]: e.get("value", "")
                            for e in c.get("env", []) if "value" in e}
        raise AssertionError("no kubelet-plugin DaemonSet in chart output")

    def test_upgrade_adopts_live_checkpoint(self, tmp_path):
        api = FakeApiServer().start()
        api.store.version = {"major": "1", "minor": "35"}
        # Split publication needs partition devices, which need the
        # DynamicSubSlice gate -- both releases run with it on.
        old_env = self._chart_env({
            "logVerbosity": 4,
            "featureGates": "DynamicSubSlice=true",
        })
        new_env = self._chart_env({
            "logVerbosity": 6,
            "featureGates": "DynamicSubSlice=true",
            "kubeletPlugin": {"publicationMode": "split"},
        })
        assert old_env["V"] == "4" and new_env["V"] == "6"
        assert new_env["PUBLICATION_MODE"] == "split"
        chart_keys = {"V", "PUBLICATION_MODE", "FEATURE_GATES"}

        def run_env(env):
            return {k: v for k, v in env.items() if k in chart_keys}

        try:
            old, old_log, _ = start_plugin(
                tmp_path, api.url, run_env(old_env), name="old")
            kubelet = FakeKubelet(str(tmp_path / "registry"))
            kubelet.wait_for_plugin(DRIVER, timeout=60)
            kube = KubeClient(host=api.url)
            kube.create("resource.k8s.io", "v1", "resourceclaims",
                        make_claim_dict("live", ["chip-2"],
                                        namespace="ns1", name="live"),
                        namespace="ns1")
            r = kubelet.prepare(DRIVER, [
                {"uid": "live", "namespace": "ns1", "name": "live"}])
            assert r.claims["live"].error == ""
            stop(old, old_log)  # rollout terminates the old pod

            new, new_log, _ = start_plugin(
                tmp_path, api.url, run_env(new_env), name="new")
            try:
                kubelet2 = FakeKubelet(str(tmp_path / "registry"))
                kubelet2.wait_for_plugin(DRIVER, timeout=60)

                # New config took effect: split publication (two slices).
                def split_published():
                    slices = [
                        s for s in kube.list("resource.k8s.io", "v1",
                                             "resourceslices")
                        if s["spec"].get("driver") == DRIVER]
                    return len(slices) == 2
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline and not split_published():
                    time.sleep(0.5)
                assert split_published(), "split mode never published"

                # The live claim survived the upgrade: the successor
                # adopted the checkpoint and can unprepare it.
                u = kubelet2.unprepare(DRIVER, ["live"])
                assert u.claims["live"].error == ""
                # ... and the chip is immediately reusable.
                kube.create("resource.k8s.io", "v1", "resourceclaims",
                            make_claim_dict("after", ["chip-2"],
                                            namespace="ns1", name="after"),
                            namespace="ns1")
                r2 = kubelet2.prepare(DRIVER, [
                    {"uid": "after", "namespace": "ns1", "name": "after"}])
                assert r2.claims["after"].error == ""
                kubelet2.unprepare(DRIVER, ["after"])
            finally:
                stop(new, new_log)
        finally:
            api.stop()


class TestCdUpDowngrade:
    """test_cd_up_downgrade.bats role: a live channel claim survives
    both rollout directions. Downgrade: the current release's v2
    checkpoint carries a v1 checksum an old reader verifies over its
    own projection of the payload. Upgrade: a v1-schema file written by
    an old release is ADOPTED by the current binary -- the live claim
    still guards its channel against double-allocation and unprepares
    cleanly, and the next write migrates the file back to v2."""

    def _run(self, root, uid, action):
        return subprocess.run(
            [sys.executable, "-m", "tests.cd_prepare_helper",
             str(root), uid, action],
            env=ENV, capture_output=True, text=True, timeout=120,
            cwd=REPO,
        )

    def test_channel_claim_survives_both_directions(self, tmp_path):
        import json

        from k8s_dra_driver_gpu_tpu.kubeletplugin.checkpoint import (
            Checkpoint,
            _checksum,
        )

        root = tmp_path / "root"
        assert self._run(root, "cd-ud-1", "prepare").returncode == 0
        cp_path = root / "checkpoint.json"
        doc = json.loads(cp_path.read_text())
        assert doc["version"] == "v2"
        assert set(doc["checksums"]) == {"v1", "v2"}

        # Downgrade leg: an old (v1) reader recomputes checksums["v1"]
        # over its projection -- it must match, or the old release
        # would refuse the file as corrupt mid-rollback.
        cp = Checkpoint.from_dict(doc)
        v1_payload = cp._payload_v1()
        assert _checksum(v1_payload) == doc["checksums"]["v1"]
        assert "cd-ud-1" in v1_payload["claims"]

        # ... and the old release rewrites the file in its own schema.
        cp_path.write_text(json.dumps({
            "version": "v1",
            "data": v1_payload,
            "checksums": {"v1": doc["checksums"]["v1"]},
        }))

        # Upgrade leg: the current binary adopts the v1 file. Proof of
        # adoption (not silent invalidation): the live claim still
        # holds channel-0, so a second claim must hit the
        # double-allocation guard.
        clash = self._run(root, "cd-ud-2", "prepare")
        assert clash.returncode != 0, clash.stdout
        assert "alloc" in (clash.stdout + clash.stderr).lower()

        done = self._run(root, "cd-ud-1", "unprepare")
        assert done.returncode == 0, done.stdout + done.stderr
        doc2 = json.loads(cp_path.read_text())
        assert doc2["version"] == "v2"  # migrated forward on write
        assert "cd-ud-1" not in json.dumps(doc2)

        # The channel is reusable after the adopted unprepare.
        again = self._run(root, "cd-ud-2", "prepare")
        assert again.returncode == 0, again.stdout + again.stderr


class TestApiserverOutage:
    """Control-plane outage resilience (test_gpu_robustness.bats
    class): with the apiserver down, prepare fails with a retriable
    per-claim ERROR (never a crash) because the claim GET cannot be
    served; when the apiserver comes back on the same endpoint with
    the same store, the SAME claim prepares successfully and the
    plugin process never restarted."""

    def test_prepare_fails_then_recovers_across_outage(self, tmp_path):
        api = FakeApiServer().start()
        port = api.port
        api_up = api  # whichever server is currently live (for finally)
        proc, log, _ = start_plugin(tmp_path, api.url, name="plugin-outage")
        try:
            kubelet = FakeKubelet(str(tmp_path / "registry"))
            kubelet.wait_for_plugin(DRIVER, timeout=60)
            kube = KubeClient(host=api.url)

            # Baseline + the claim we will prepare during/after outage.
            for uid, chip in (("out-base", "chip-0"), ("out-c2", "chip-1")):
                kube.create(
                    "resource.k8s.io", "v1", "resourceclaims",
                    make_claim_dict(uid, [chip], namespace="ns1", name=uid),
                    namespace="ns1")
            r = kubelet.prepare(DRIVER, [
                {"uid": "out-base", "namespace": "ns1", "name": "out-base"}])
            assert r.claims["out-base"].error == ""

            # Outage: the plugin must degrade to per-claim errors, not die.
            api.stop()
            api_up = None
            r = kubelet.prepare(DRIVER, [
                {"uid": "out-c2", "namespace": "ns1", "name": "out-c2"}])
            assert r.claims["out-c2"].error != ""
            assert proc.poll() is None, "plugin died during apiserver outage"

            # Recovery: same port, same store (an apiserver restart, not
            # a wipe). The identical claim now prepares.
            api_up = FakeApiServer(store=api.store, port=port).start()
            deadline = time.monotonic() + 30
            last = None
            while time.monotonic() < deadline:
                r = kubelet.prepare(DRIVER, [
                    {"uid": "out-c2", "namespace": "ns1",
                     "name": "out-c2"}])
                last = r.claims["out-c2"].error
                if last == "":
                    break
                time.sleep(0.5)
            assert last == "", f"prepare never recovered: {last}"
            assert proc.poll() is None
            for uid in ("out-base", "out-c2"):
                u = kubelet.unprepare(DRIVER, [uid])
                assert u.claims[uid].error == ""
        finally:
            stop(proc, log)
            if api_up is not None:
                api_up.stop()


class TestStaleClaimGC:
    """The stale-claim GC against the LIVE binary (cleanup.go role,
    10-min cadence tightened via TPU_DRA_CLEANUP_INTERVAL_S): deleting
    a prepared claim's API object makes the plugin unprepare it within
    the cadence, releasing its chip for the next claim -- without any
    kubelet unprepare call."""

    def test_deleted_claim_reaped_and_chip_released(self, tmp_path):
        api = FakeApiServer().start()
        proc, log, log_path = start_plugin(
            tmp_path, api.url, {"TPU_DRA_CLEANUP_INTERVAL_S": "1"},
            name="plugin-gc")
        try:
            kubelet = FakeKubelet(str(tmp_path / "registry"))
            kubelet.wait_for_plugin(DRIVER, timeout=60)
            kube = KubeClient(host=api.url)

            kube.create(
                "resource.k8s.io", "v1", "resourceclaims",
                make_claim_dict("gc-victim", ["chip-0"], namespace="ns1",
                                name="gc-victim"), namespace="ns1")
            r = kubelet.prepare(DRIVER, [
                {"uid": "gc-victim", "namespace": "ns1",
                 "name": "gc-victim"}])
            assert r.claims["gc-victim"].error == ""

            # The user deletes the claim object; the kubelet never calls
            # unprepare (pod gone with it). The GC must notice.
            kube.delete("resource.k8s.io", "v1", "resourceclaims",
                        "gc-victim", namespace="ns1")
            deadline = time.monotonic() + 30
            reaped = False
            while time.monotonic() < deadline:
                if "unpreparing stale claim gc-victim" in \
                        log_path.read_text():
                    reaped = True
                    break
                time.sleep(0.5)
            assert reaped, "GC never reaped the deleted claim"

            # chip-0 is free again: an exclusive claim on it prepares.
            kube.create(
                "resource.k8s.io", "v1", "resourceclaims",
                make_claim_dict("gc-next", ["chip-0"], namespace="ns1",
                                name="gc-next"), namespace="ns1")
            r = kubelet.prepare(DRIVER, [
                {"uid": "gc-next", "namespace": "ns1", "name": "gc-next"}])
            assert r.claims["gc-next"].error == ""
            kubelet.unprepare(DRIVER, ["gc-next"])
        finally:
            stop(proc, log)
            api.stop()


class TestDebugAndMetricsSurfaces:
    """Live-binary observability (test_basics.bats SIGUSR2 +
    'kubelet-plugin exposes Prometheus metrics' analogs): SIGUSR2
    makes the running plugin write a thread-stack dump, and its
    metrics port serves the DRA request histograms after real
    traffic."""

    def test_sigusr2_dump_and_metrics_scrape(self, tmp_path):
        import socket
        import urllib.request

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            mport = s.getsockname()[1]
        dump = tmp_path / "stacks.dump"
        api = FakeApiServer().start()
        proc, log, _ = start_plugin(
            tmp_path, api.url,
            {"METRICS_PORT": str(mport),
             "TPU_DRA_STACK_DUMP": str(dump)},
            name="plugin-debug")
        try:
            kubelet = FakeKubelet(str(tmp_path / "registry"))
            kubelet.wait_for_plugin(DRIVER, timeout=60)
            kube = KubeClient(host=api.url)
            kube.create(
                "resource.k8s.io", "v1", "resourceclaims",
                make_claim_dict("dbg-1", ["chip-0"], namespace="ns1",
                                name="dbg-1"), namespace="ns1")
            r = kubelet.prepare(DRIVER, [
                {"uid": "dbg-1", "namespace": "ns1", "name": "dbg-1"}])
            assert r.claims["dbg-1"].error == ""

            # SIGUSR2 -> stack dump at the overridden path, with the
            # serving threads visible.
            proc.send_signal(signal.SIGUSR2)
            deadline = time.monotonic() + 15
            text = ""
            while time.monotonic() < deadline:
                # Poll for CONTENT, not existence: the handler's
                # open-then-write is not atomic.
                if dump.exists() and "MainThread" in (
                        text := dump.read_text()):
                    break
                time.sleep(0.2)
            assert "MainThread" in text, \
                f"SIGUSR2 never produced a full stack dump: {text[:200]!r}"
            assert proc.poll() is None  # the signal must not kill it

            # Prometheus scrape reflects the real prepare above.
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics", timeout=10
            ).read().decode()
            assert "tpu_dra_request_duration_seconds_bucket" in body
            assert 'operation="NodePrepareResources"' in body
            assert "tpu_dra_prepared_devices 1.0" in body
            kubelet.unprepare(DRIVER, ["dbg-1"])
        finally:
            stop(proc, log)
            api.stop()
