"""Permanent-failure recovery tier (ISSUE 6): failure escalation
(pkg/recovery.FailureDetector + kubeletplugin/health.py), the claim
eviction & migration controller (pkg/recovery.EvictionController), the
cross-layer node reconcile sweep (kubeletplugin/reconcile.py), and the
eviction state machine's durability + interleaving coverage.

The acceptance bar under test: after ANY permanent failure -- node
killed, node deleted, chip fatally tainted, plugin wiped, controller
crashed mid-eviction -- every affected claim converges to re-allocated-
on-surviving-capacity or cleanly-Failed, with zero leaked carve-outs,
CDI specs, or leases, and the sweep repairs hand-planted orphans in one
pass."""

import os
import time

import pytest

from k8s_dra_driver_gpu_tpu.kubeletplugin.checkpoint import (
    CheckpointedClaim,
    CheckpointedDevice,
    ClaimState,
)
from k8s_dra_driver_gpu_tpu.kubeletplugin.device_state import (
    Config,
    DeviceState,
)
from k8s_dra_driver_gpu_tpu.kubeletplugin.health import (
    QuarantineTracker,
)
from k8s_dra_driver_gpu_tpu.kubeletplugin.reconcile import (
    CDStateReconciler,
    NodeStateReconciler,
)
from k8s_dra_driver_gpu_tpu.pkg import faults
from k8s_dra_driver_gpu_tpu.pkg.analysis.statemachine import (
    CheckpointTransitionError,
    EVICTION_DEALLOCATED,
    EVICTION_DRAINING,
    EVICTION_PLANNED,
)
from k8s_dra_driver_gpu_tpu.pkg.faults import InjectedCrash
from k8s_dra_driver_gpu_tpu.pkg.kubeclient import FakeKubeClient
from k8s_dra_driver_gpu_tpu.pkg.metrics import RecoveryMetrics
from k8s_dra_driver_gpu_tpu.pkg.recovery import (
    EvictionController,
    FAILED_TAINT_KEY,
    FailureDetector,
    PERMANENT_FAILURE_CONDITION,
)
from k8s_dra_driver_gpu_tpu.pkg.scheduler import DraScheduler
from k8s_dra_driver_gpu_tpu.pkg.sliceutil import publish_resource_slices

from tests.fake_kube import make_claim, make_claim_dict

RES = ("resource.k8s.io", "v1")
DRIVER = "tpu.dra.dev"


# -- cluster scaffolding ------------------------------------------------------


def apply_class(kube, name=DRIVER):
    kube.create(*RES, "deviceclasses", {
        "apiVersion": "resource.k8s.io/v1", "kind": "DeviceClass",
        "metadata": {"name": name},
        "spec": {"selectors": [{"cel": {
            "expression": f'device.driver == "{name}"'}}]},
    })


def node_slices(node, chips=4, taints_by_chip=None):
    devices = []
    for j in range(chips):
        dev = {"name": f"chip-{j}", "attributes": {
            "type": {"string": "tpu-chip"}, "index": {"int": j}}}
        if taints_by_chip and j in taints_by_chip:
            dev["taints"] = list(taints_by_chip[j])
        devices.append(dev)
    return [{
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceSlice",
        "metadata": {"name": f"{node}-{DRIVER}"},
        "spec": {"driver": DRIVER, "nodeName": node,
                 "pool": {"name": node, "generation": 1,
                          "resourceSliceCount": 1},
                 "devices": devices},
    }]


def add_node(kube, name, ready=True):
    kube.create("", "v1", "nodes", {
        "metadata": {"name": name, "labels": {}},
        "status": {"conditions": [
            {"type": "Ready", "status": "True" if ready else "False"}]},
    })


def set_ready(kube, name, ready):
    kube.patch("", "v1", "nodes", name, {"status": {"conditions": [
        {"type": "Ready", "status": "True" if ready else "False"}]}})


def make_pending_claim(kube, name, count=1, ns="default", gang=None):
    spec = {"devices": {"requests": [{
        "name": "tpu",
        "exactly": {"deviceClassName": DRIVER, **(
            {"count": count} if count != 1 else {})},
    }]}}
    if gang:
        spec["devices"]["config"] = [{"opaque": {
            "driver": DRIVER,
            "parameters": {"kind": "ComputeDomainChannelConfig",
                           "domainID": gang},
        }}]
    kube.create(*RES, "resourceclaims", {
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
        "metadata": {"name": name, "namespace": ns},
        "spec": spec,
    }, namespace=ns)


def make_pod(kube, name, claim_name, ns="default"):
    kube.create("", "v1", "pods", {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"containers": [{"name": "c"}],
                 "resourceClaims": [{"name": "tpu",
                                     "resourceClaimName": claim_name}]},
    }, namespace=ns)


def alloc_node(kube, name, ns="default"):
    claim = kube.get(*RES, "resourceclaims", name, namespace=ns)
    alloc = claim.get("status", {}).get("allocation")
    if not alloc:
        return None
    return alloc["nodeSelector"]["nodeSelectorTerms"][0][
        "matchFields"][0]["values"][0]


def condition(kube, name, ns="default"):
    claim = kube.get(*RES, "resourceclaims", name, namespace=ns)
    for c in claim.get("status", {}).get("conditions") or []:
        if c.get("type") == PERMANENT_FAILURE_CONDITION:
            return c
    return None


@pytest.fixture()
def cluster(tmp_path):
    """(kube, scheduler-with-recovery, controller): 2 nodes x 4 chips,
    instant NotReady escalation, direct (sync_once) drive."""
    fake = FakeKubeClient()
    apply_class(fake)
    for node in ("node-a", "node-b"):
        add_node(fake, node)
        publish_resource_slices(fake, node_slices(node))
    sched = DraScheduler(fake)
    ctrl = EvictionController(fake, str(tmp_path / "recovery"),
                              notready_grace_s=0.0, deadline_s=60.0)
    sched.attach_recovery(ctrl)
    return fake, sched, ctrl


def settle(sched, passes=6, sleep=0.0):
    for _ in range(passes):
        if sleep:
            time.sleep(sleep)
        sched.sync_once()


# -- failure escalation -------------------------------------------------------


class TestFailureEscalation:
    def test_notready_past_deadline_migrates_claims(self, cluster):
        fake, sched, ctrl = cluster
        for i in range(3):
            make_pending_claim(fake, f"c{i}")
            make_pod(fake, f"c{i}-pod", f"c{i}")
        settle(sched, 2)
        placed = {f"c{i}": alloc_node(fake, f"c{i}") for i in range(3)}
        assert all(placed.values())
        victims = [n for n, node in placed.items() if node == "node-b"]
        assert victims, "expected spreading onto node-b"

        set_ready(fake, "node-b", False)
        settle(sched)
        for name in victims:
            assert alloc_node(fake, name) == "node-a"
            cond = condition(fake, name)
            assert cond["status"] == "False"
            assert cond["reason"] == "Recovered"
        # Fully retired: nothing in flight, failed node durably tainted.
        assert ctrl.active_evictions() == {}
        node = fake.get("", "v1", "nodes", "node-b")
        assert any(t["key"] == FAILED_TAINT_KEY
                   for t in node["spec"]["taints"])

    def test_notready_within_grace_is_not_escalated(self, tmp_path):
        fake = FakeKubeClient()
        apply_class(fake)
        for node in ("node-a", "node-b"):
            add_node(fake, node)
            publish_resource_slices(fake, node_slices(node))
        sched = DraScheduler(fake)
        ctrl = EvictionController(fake, str(tmp_path / "r"),
                                  notready_grace_s=3600.0)
        sched.attach_recovery(ctrl)
        make_pending_claim(fake, "c0")
        settle(sched, 2)
        set_ready(fake, alloc_node(fake, "c0"), False)
        settle(sched, 3)
        assert ctrl.active_evictions() == {}
        assert condition(fake, "c0") is None

    def test_node_deletion_retires_slices_and_migrates(self, cluster):
        fake, sched, ctrl = cluster
        make_pending_claim(fake, "c0", count=4)  # fills one node
        settle(sched, 2)
        victim_node = alloc_node(fake, "c0")
        fake.delete("", "v1", "nodes", victim_node)
        settle(sched)
        # The dead node's slices are orphans: retired so the snapshot
        # stops offering ghost capacity.
        assert all(
            s["spec"].get("nodeName") != victim_node
            for s in fake.list(*RES, "resourceslices"))
        assert alloc_node(fake, "c0") not in (None, victim_node)

    def test_fatal_device_taint_evicts_only_its_claim(self, cluster):
        fake, sched, ctrl = cluster
        for i in range(2):
            make_pending_claim(fake, f"c{i}")
        settle(sched, 2)
        claim = fake.get(*RES, "resourceclaims", "c0",
                         namespace="default")
        result = claim["status"]["allocation"]["devices"]["results"][0]
        node, device = result["pool"], result["device"]
        # The health layer publishes the fatal taint on the chip.
        chip_idx = int(device.split("-")[1])
        publish_resource_slices(fake, node_slices(node, taints_by_chip={
            chip_idx: [{"key": FAILED_TAINT_KEY, "value": "true",
                        "effect": "NoExecute"}]}))
        settle(sched)
        cond = condition(fake, "c0")
        assert cond and cond["reason"] == "Recovered"
        new = fake.get(*RES, "resourceclaims", "c0", namespace="default")
        new_result = new["status"]["allocation"]["devices"]["results"][0]
        assert (new_result["pool"], new_result["device"]) != (node, device)
        # The healthy claim was never touched.
        assert condition(fake, "c1") is None
        assert ctrl.active_evictions() == {}

    def test_deadline_exceeded_fails_cleanly(self, tmp_path):
        """One node, no surviving capacity: the eviction must retire as
        cleanly Failed (condition, no allocation, no record) instead of
        sitting mid-eviction forever."""
        fake = FakeKubeClient()
        apply_class(fake)
        add_node(fake, "node-a")
        publish_resource_slices(fake, node_slices("node-a"))
        sched = DraScheduler(fake)
        ctrl = EvictionController(fake, str(tmp_path / "r"),
                                  notready_grace_s=0.0, deadline_s=0.05)
        sched.attach_recovery(ctrl)
        make_pending_claim(fake, "c0")
        settle(sched, 2)
        assert alloc_node(fake, "c0") == "node-a"
        set_ready(fake, "node-a", False)
        settle(sched, 3)
        time.sleep(0.06)  # blow the per-claim recovery deadline
        settle(sched, 2)
        cond = condition(fake, "c0")
        assert cond["status"] == "True"
        assert cond["reason"] == "RecoveryDeadlineExceeded"
        assert alloc_node(fake, "c0") is None
        assert ctrl.active_evictions() == {}

    def test_detector_treats_statusless_nodes_as_ready(self):
        det = FailureDetector(notready_grace_s=0.0)
        det.observe_nodes([{"metadata": {"name": "bare"}}])
        assert det.permanently_failed == set()
        # Deletion of a known node IS positive evidence.
        det.observe_nodes([])
        assert det.permanently_failed == {"bare"}
        det.observe_nodes([{"metadata": {"name": "bare"}}])
        assert det.permanently_failed == set()


# -- quarantine -> permanent failure (health layer) ---------------------------


class TestQuarantineEscalation:
    def flap(self, tracker, clock, device="chip-0", cycles=1):
        """Drive one full quarantine cycle: 3 flaps to escalate, then
        clean past hysteresis to release."""
        from k8s_dra_driver_gpu_tpu.kubeletplugin.health import (
            DeviceTaint,
        )

        taint = [DeviceTaint(device=device, key="tpu.dra.dev/thermal",
                             value="true", effect="")]
        for _ in range(cycles):
            for step in range(6):
                clock[0] += 5.0
                tracker.observe(taint if step % 2 == 0 else [])
            clock[0] += 1000.0
            tracker.observe([])

    def test_repeated_quarantines_escalate_to_sticky_failure(self):
        clock = [0.0]
        failed = []
        tracker = QuarantineTracker(
            threshold=3, window_s=60.0, hysteresis_s=120.0,
            fatal_after=3, on_failed=failed.append,
            clock=lambda: clock[0])
        self.flap(tracker, clock, cycles=2)
        assert tracker.failed == frozenset()
        assert tracker.total_quarantines == 2
        self.flap(tracker, clock, cycles=1)
        assert tracker.failed == {"chip-0"}
        assert failed == ["chip-0"]
        # Sticky: hysteresis never releases a failed chip, and its
        # taint is NoExecute under the key recovery escalates on.
        clock[0] += 10_000.0
        taints = tracker.observe([])
        assert [(t.key, t.effect) for t in taints
                if t.device == "chip-0"] == \
            [(FAILED_TAINT_KEY, "NoExecute")]

    def test_mark_failed_is_direct_and_idempotent(self):
        tracker = QuarantineTracker()
        tracker.mark_failed("chip-1")
        tracker.mark_failed("chip-1")
        assert tracker.failed == {"chip-1"}
        assert tracker.total_failures == 1
        # A failed chip is past all flap bookkeeping.
        from k8s_dra_driver_gpu_tpu.kubeletplugin.health import (
            DeviceTaint,
        )

        out = tracker.observe([DeviceTaint(
            device="chip-1", key="tpu.dra.dev/thermal", value="true",
            effect="")])
        assert [(t.key, t.effect) for t in out] == \
            [(FAILED_TAINT_KEY, "NoExecute")]


# -- gang eviction + planning -------------------------------------------------


class TestEvictionPlanning:
    def test_gang_evicts_as_a_unit(self, cluster):
        """One dead member strands the rendezvous: the healthy
        companion drains too (GangCompanionFailed), and the plan's
        score records the disruption."""
        fake, sched, ctrl = cluster
        make_pending_claim(fake, "g0", gang="cd-uid-1")
        make_pending_claim(fake, "g1", gang="cd-uid-1")
        settle(sched, 2)
        nodes = {n: alloc_node(fake, n) for n in ("g0", "g1")}
        assert set(nodes.values()) == {"node-a", "node-b"}
        dead = nodes["g0"]
        set_ready(fake, dead, False)
        sched.sync_once()  # detect + plan + drain
        records = ctrl._checkpoint.get().claims
        metas = {rec.name: rec.devices[0].live for rec in
                 records.values()}
        assert set(metas) == {"g0", "g1"}
        assert all(m["disruption"] == 1 for m in metas.values())
        companion = "g1" if nodes["g1"] != dead else "g0"
        assert condition(fake, companion)["reason"] in (
            "GangCompanionFailed", "NodeFailed")
        settle(sched)
        # Both re-placed on the survivor; nothing in flight.
        survivor = "node-a" if dead == "node-b" else "node-b"
        assert alloc_node(fake, "g0") == survivor
        assert alloc_node(fake, "g1") == survivor
        assert ctrl.active_evictions() == {}

    def test_bounded_concurrent_evictions(self, tmp_path):
        fake = FakeKubeClient()
        apply_class(fake)
        for node in ("node-a", "node-b", "node-c"):
            add_node(fake, node)
            publish_resource_slices(fake, node_slices(node, chips=4))
        sched = DraScheduler(fake)
        ctrl = EvictionController(fake, str(tmp_path / "r"),
                                  notready_grace_s=0.0,
                                  max_concurrent=1, deadline_s=60.0)
        sched.attach_recovery(ctrl)
        for i in range(4):
            make_pending_claim(fake, f"c{i}")
        settle(sched, 2)
        victims = [f"c{i}" for i in range(4)
                   if alloc_node(fake, f"c{i}") in ("node-b", "node-c")]
        set_ready(fake, "node-b", False)
        set_ready(fake, "node-c", False)
        sched.sync_once()
        # The cap admits ONE eviction; the rest are deferred, not lost.
        assert len(ctrl.active_evictions()) == 1
        settle(sched, passes=14)  # serialized: ~4 passes per eviction
        for name in victims:
            assert alloc_node(fake, name) == "node-a"
        assert ctrl.active_evictions() == {}

    def test_young_claim_admitted_before_old_gang(self, tmp_path):
        """The age-cost satellite: under the concurrency cap the
        planner admits the YOUNG singleton's migration first --
        moving a long-running claim throws away hours of work, so
        uptime now weighs into the 2502.01909 score alongside device
        count and gang disruption."""
        fake = FakeKubeClient()
        apply_class(fake)
        add_node(fake, "node-b")
        publish_resource_slices(fake, node_slices("node-b"))
        sched = DraScheduler(fake)
        ctrl = EvictionController(fake, str(tmp_path / "r"),
                                  notready_grace_s=0.0,
                                  max_concurrent=1, deadline_s=60.0)
        sched.attach_recovery(ctrl)
        # An OLD claim (years of uptime) and a YOUNG one (no
        # creationTimestamp = brand new), both landing on node-b.
        for name, created in (("old", "2020-01-01T00:00:00Z"),
                              ("young", None)):
            meta = {"name": name, "namespace": "default"}
            if created:
                meta["creationTimestamp"] = created
            fake.create(*RES, "resourceclaims", {
                "apiVersion": "resource.k8s.io/v1",
                "kind": "ResourceClaim", "metadata": meta,
                "spec": {"devices": {"requests": [{
                    "name": "tpu",
                    "exactly": {"deviceClassName": DRIVER}}]}},
            }, namespace="default")
        settle(sched, 2)
        assert alloc_node(fake, "old") == "node-b"
        assert alloc_node(fake, "young") == "node-b"
        add_node(fake, "node-a")
        publish_resource_slices(fake, node_slices("node-a"))
        set_ready(fake, "node-b", False)
        sched.sync_once()
        young_uid = fake.get(*RES, "resourceclaims", "young",
                             namespace="default")["metadata"]["uid"]
        # The cap admits exactly ONE eviction: the young claim's.
        assert list(ctrl.active_evictions()) == [young_uid]
        settle(sched, passes=14)
        assert ctrl.active_evictions() == {}
        for name in ("old", "young"):
            assert alloc_node(fake, name) == "node-a"


# -- durability: crash-at-every-fault-point + resume --------------------------


class TestEvictionDurability:
    @pytest.fixture()
    def failed_cluster(self, tmp_path):
        fake = FakeKubeClient()
        apply_class(fake)
        for node in ("node-a", "node-b"):
            add_node(fake, node)
            publish_resource_slices(fake, node_slices(node))
        sched = DraScheduler(fake)
        root = str(tmp_path / "recovery")
        ctrl = EvictionController(fake, root, notready_grace_s=0.0,
                                  deadline_s=60.0)
        sched.attach_recovery(ctrl)
        make_pending_claim(fake, "c0")
        make_pod(fake, "c0-pod", "c0")
        settle(sched, 2)
        set_ready(fake, alloc_node(fake, "c0"), False)
        return fake, sched, ctrl, root

    @pytest.mark.parametrize("point", [
        "recovery.sync", "recovery.plan", "recovery.drain",
        "recovery.dealloc",
    ])
    def test_controller_crash_resumes_idempotently(
            self, failed_cluster, point):
        """InjectedCrash at every controller fault point, then a FRESH
        controller on the same state root: the eviction resumes from
        the durable record and converges -- the mid-eviction-crash
        acceptance scenario."""
        fake, sched, ctrl, root = failed_cluster
        with faults.inject(point, mode="crash", count=1):
            for _ in range(4):
                try:
                    ctrl.sync_once()
                except InjectedCrash:
                    break
            else:
                pytest.fail(f"{point} never fired")
        # The dead controller's replacement resumes from the durable
        # eviction records (and re-detects the failed node).
        resumed = EvictionController(fake, root, notready_grace_s=0.0,
                                     deadline_s=60.0)
        sched.attach_recovery(resumed)
        settle(sched)
        assert alloc_node(fake, "c0") not in (None,) and \
            alloc_node(fake, "c0") == "node-a" or \
            alloc_node(fake, "c0") == "node-b"
        cond = condition(fake, "c0")
        assert cond and cond["reason"] == "Recovered"
        assert resumed.active_evictions() == {}

    def test_claim_deleted_mid_eviction_cancels(self, failed_cluster):
        fake, sched, ctrl, root = failed_cluster
        ctrl.sync_once()  # plan + drain
        assert ctrl.active_evictions()
        fake.delete(*RES, "resourceclaims", "c0", namespace="default")
        settle(sched, 2)
        assert ctrl.active_evictions() == {}

    def test_illegal_stage_skip_fails_the_commit(self, tmp_path):
        """absent -> Draining (a drain without its durable plan) is
        exactly what the eviction TransitionPolicy must refuse."""
        fake = FakeKubeClient()
        ctrl = EvictionController(fake, str(tmp_path / "r"))
        rec = CheckpointedClaim(
            uid="u1", namespace="default", name="c",
            state=EVICTION_DRAINING,
            devices=[CheckpointedDevice(canonical_name="eviction",
                                        kind="eviction", live={})])
        with pytest.raises(RuntimeError) as err:
            ctrl._checkpoint.update_claim("u1", rec)
        assert isinstance(err.value.__cause__,
                          CheckpointTransitionError)
        # The legal ladder commits fine.
        for state in (EVICTION_PLANNED, EVICTION_DRAINING,
                      EVICTION_DEALLOCATED):
            rec = CheckpointedClaim(
                uid="u1", namespace="default", name="c", state=state,
                devices=rec.devices)
            ctrl._checkpoint.update_claim("u1", rec)
        ctrl._checkpoint.update_claim("u1", None)

    def test_generated_claim_with_dead_owner_is_gcd(self, tmp_path):
        """A template-generated claim whose owner pod died with the
        node is deleted, not deallocated: the recreated pod generates
        a FRESH claim (keeping the orphan would hold devices for a
        consumer that can never return)."""
        fake = FakeKubeClient()
        apply_class(fake)
        for node in ("node-a", "node-b"):
            add_node(fake, node)
            publish_resource_slices(fake, node_slices(node))
        sched = DraScheduler(fake)
        ctrl = EvictionController(fake, str(tmp_path / "r"),
                                  notready_grace_s=0.0)
        sched.attach_recovery(ctrl)
        make_pending_claim(fake, "gen-c")
        fake.patch(*RES, "resourceclaims", "gen-c", {
            "metadata": {"ownerReferences": [{
                "apiVersion": "v1", "kind": "Pod", "name": "owner",
                "uid": "pod-uid", "controller": True}]},
        }, namespace="default")
        settle(sched, 2)
        set_ready(fake, alloc_node(fake, "gen-c"), False)
        settle(sched)
        with pytest.raises(Exception):
            fake.get(*RES, "resourceclaims", "gen-c",
                     namespace="default")
        assert ctrl.active_evictions() == {}


# -- node-plugin reconcile sweep ----------------------------------------------


class TestNodeReconcileSweep:
    def make_state(self, tmp_path, name="sweep"):
        return DeviceState(Config.mock(root=str(tmp_path / name),
                                       topology="v5e-4"))

    def register_claim(self, kube, uid, devices):
        obj = make_claim_dict(uid, devices)
        obj["metadata"]["name"] = uid
        kube.create(*RES, "resourceclaims", obj, namespace="default")
        return obj

    def test_hand_planted_orphans_repaired_in_one_sweep(self, tmp_path):
        from k8s_dra_driver_gpu_tpu.kubeletplugin.cleanup import (
            CheckpointCleanupManager,
        )
        from k8s_dra_driver_gpu_tpu.kubeletplugin.subslice import (
            SubSliceLiveTuple,
            SubSliceSpecTuple,
        )

        kube = FakeKubeClient()
        state = self.make_state(tmp_path)
        self.register_claim(kube, "live-1", ["chip-0"])
        state.prepare(make_claim("live-1", ["chip-0"]))
        # Hand-planted orphans in every layer: a live carve-out, a CDI
        # spec, and a reservation lease, none owned by any claim.
        state._registry.create(SubSliceLiveTuple(
            spec=SubSliceSpecTuple.from_canonical_name("ss-2x1-0"),
            uuid="tpu-ss-orphan"))
        state._cdi.create_claim_spec_file("ghost-uid", {}, None)
        state._leases.write("ghost-uid")
        metrics = RecoveryMetrics()
        rec = NodeStateReconciler(
            state, kube,
            cleanup=CheckpointCleanupManager(state, kube),
            metrics=metrics)
        counts = rec.reconcile_once()
        assert counts["carveout"] == 1
        assert counts["cdi_spec"] == 1
        assert counts["lease"] == 1
        assert "tpu-ss-orphan" not in state._registry.list()
        assert state._cdi.read_spec("ghost-uid") is None
        assert state._leases.read("ghost-uid") is None
        # The live claim's artifacts all survived.
        assert state._cdi.read_spec("live-1") is not None
        assert "live-1" in state.prepared_claims()
        # A second sweep finds a converged node.
        assert not any(rec.reconcile_once().values())

    def test_stale_claim_unprepared_and_devices_gone_declared(
            self, tmp_path):
        from k8s_dra_driver_gpu_tpu.kubeletplugin.cleanup import (
            CheckpointCleanupManager,
        )

        kube = FakeKubeClient()
        state = self.make_state(tmp_path)
        self.register_claim(kube, "stale-1", ["chip-0"])
        state.prepare(make_claim("stale-1", ["chip-0"]))
        kube.delete(*RES, "resourceclaims", "stale-1",
                    namespace="default")
        # A completed record whose device fell off the host: the node
        # can only report it -- the claim needs migration.
        self.register_claim(kube, "gone-dev", ["chip-9"])
        for stage in (ClaimState.PREPARE_STARTED,
                      ClaimState.PREPARE_COMPLETED):
            state._checkpoint.update_claim("gone-dev", CheckpointedClaim(
                uid="gone-dev", namespace="default", name="gone-dev",
                state=stage.value,
                devices=[CheckpointedDevice(canonical_name="chip-9",
                                            kind="chip")]))
        rec = NodeStateReconciler(
            state, kube,
            cleanup=CheckpointCleanupManager(state, kube))
        counts = rec.reconcile_once()
        assert counts["stale_claim"] == 1
        assert "stale-1" not in state.prepared_claims()
        assert counts["devices_gone"] == 1
        claim = kube.get(*RES, "resourceclaims", "gone-dev",
                         namespace="default")
        conds = {c["type"]: c for c in claim["status"]["conditions"]}
        assert conds[PERMANENT_FAILURE_CONDITION]["reason"] == \
            "DevicesGone"

    def test_deallocated_claim_is_drained_by_sweep(self, tmp_path):
        """The plugin half of the controller's drain: once the
        eviction deallocates (or re-places) a claim, this node's
        record/carve-out/CDI state is torn down through the normal
        unprepare -- no kubelet call required."""
        from k8s_dra_driver_gpu_tpu.kubeletplugin.cleanup import (
            CheckpointCleanupManager,
        )

        kube = FakeKubeClient()
        state = self.make_state(tmp_path)
        self.register_claim(kube, "moving", ["chip-0"])
        state.prepare(make_claim("moving", ["chip-0"]))
        rec = NodeStateReconciler(
            state, kube,
            cleanup=CheckpointCleanupManager(state, kube))
        # Still allocated here: the sweep must NOT touch it.
        assert rec.reconcile_once()["moved_claim"] == 0
        assert "moving" in state.prepared_claims()
        kube.patch(*RES, "resourceclaims", "moving",
                   {"status": {"allocation": None}},
                   namespace="default")
        counts = rec.reconcile_once()
        assert counts["moved_claim"] == 1
        assert "moving" not in state.prepared_claims()
        assert state._cdi.read_spec("moving") is None

    def test_same_device_name_on_another_node_still_drains(
            self, tmp_path):
        """Device names are node-local indices: a claim re-placed on
        ANOTHER node that also hands out chip-0 must still be drained
        here (node identity via the allocation's nodeSelector), while
        one positively pinned HERE -- or with no node evidence at all
        -- is kept."""
        from k8s_dra_driver_gpu_tpu.kubeletplugin.cleanup import (
            CheckpointCleanupManager,
        )

        def selector(node):
            return {"nodeSelectorTerms": [{"matchFields": [{
                "key": "metadata.name", "operator": "In",
                "values": [node]}]}]}

        kube = FakeKubeClient()
        state = self.make_state(tmp_path)
        self.register_claim(kube, "roamer", ["chip-0"])
        kube.patch(*RES, "resourceclaims", "roamer", {
            "status": {"allocation": {
                "nodeSelector": selector("node-0")}}},
            namespace="default")
        state.prepare(make_claim("roamer", ["chip-0"]))
        rec = NodeStateReconciler(
            state, kube,
            cleanup=CheckpointCleanupManager(state, kube),
            node_name="node-0")
        # Pinned here: kept. No node evidence (plain test claim): kept.
        assert rec.reconcile_once()["moved_claim"] == 0
        assert "roamer" in state.prepared_claims()
        # Re-placed on node-1, which ALSO calls its chip "chip-0".
        kube.patch(*RES, "resourceclaims", "roamer", {
            "status": {"allocation": {
                "nodeSelector": selector("node-1")}}},
            namespace="default")
        counts = rec.reconcile_once()
        assert counts["moved_claim"] == 1
        assert "roamer" not in state.prepared_claims()

    @pytest.mark.parametrize("point,mode", [
        ("segment:prep_devices", "crash"),
        ("ckpt.write", "crash"),
        ("ckpt.fsync", "crash"),
    ])
    def test_crash_during_eviction_unprepare_then_sweep_restores(
            self, tmp_path, point, mode):
        """The eviction drain drives unprepare on the node; a crash at
        ANY fault point mid-flight (prepare middle for the re-placed
        claim, checkpoint write, the write-vs-fsync window) must leave
        a state a FRESH plugin + one sweep fully repairs: no orphaned
        leases, carve-outs, or CDI specs."""
        from k8s_dra_driver_gpu_tpu.kubeletplugin.cleanup import (
            CheckpointCleanupManager,
        )

        kube = FakeKubeClient()
        root = tmp_path / "crashy"
        state = DeviceState(Config.mock(root=str(root),
                                        topology="v5e-4"))
        self.register_claim(kube, "victim", ["chip-0"])
        state.prepare(make_claim("victim", ["chip-0"]))
        # A dynamic carve-out claim: the class whose partial teardown
        # leaks hardware state if recovery is wrong. Must not overlap
        # the chip-0 claim above.
        chip0_cores = set(state._cores_of("chip-0"))
        ss_device = next(
            n for n in sorted(state.allocatable)
            if n.startswith("ss-")
            and not chip0_cores & set(state._cores_of(n)))
        self.register_claim(kube, "carved", [ss_device])
        state.prepare(make_claim("carved", [ss_device]))
        # The eviction controller deallocated + deleted both claims;
        # the node now unprepares and crashes mid-flight.
        kube.delete(*RES, "resourceclaims", "victim",
                    namespace="default")
        kube.delete(*RES, "resourceclaims", "carved",
                    namespace="default")
        with faults.inject(point, mode=mode, count=1):
            for uid in ("victim", "carved"):
                try:
                    state.unprepare(uid)
                except (InjectedCrash, RuntimeError, OSError):
                    pass
        # Process death: a fresh plugin reconciles on startup, then the
        # sweep finishes the cross-layer repair.
        fresh = DeviceState(Config.mock(root=str(root),
                                        topology="v5e-4"))
        rec = NodeStateReconciler(
            fresh, kube,
            cleanup=CheckpointCleanupManager(fresh, kube))
        rec.reconcile_once()
        rec.reconcile_once()  # idempotent; second pass finds nothing
        assert fresh.prepared_claims() == {}
        assert fresh._registry.list() == {}
        assert fresh._cdi.list_claim_uids() == []
        leases_dir = os.path.join(str(root), "leases")
        assert [f for f in os.listdir(leases_dir)
                if f.endswith(".json")] == []


# -- CD plugin sweep (gang unwind on surviving nodes) -------------------------


class TestCDSweep:
    def test_stale_cd_claim_unprepares_and_label_drops(self, tmp_path):
        from k8s_dra_driver_gpu_tpu.computedomain import NODE_LABEL
        from k8s_dra_driver_gpu_tpu.computedomain.plugin.device_state \
            import CDDeviceState

        fake = FakeKubeClient()
        fake.create("", "v1", "nodes",
                    {"metadata": {"name": "cd-node", "labels": {}}})
        fake.create("resource.tpu.dra", "v1beta1", "computedomains", {
            "metadata": {"name": "cd", "uid": "cd-uid",
                         "namespace": "default"},
            "spec": {"numNodes": 1},
            "status": {"status": "Ready", "nodes": [
                {"name": "cd-node", "status": "Ready", "index": 0,
                 "ipAddress": "10.0.0.1"}]},
        }, namespace="default")
        state = CDDeviceState(root=str(tmp_path / "cd"), kube=fake,
                              node_name="cd-node", use_informer=False)
        obj = make_claim_dict(
            "ch-1", ["channel-0"],
            driver="compute-domain.tpu.dra.dev",
            configs=[{"parameters": {
                "apiVersion": "resource.tpu.dra/v1beta1",
                "kind": "ComputeDomainChannelConfig",
                "domainID": "cd-uid",
            }}])
        obj["metadata"]["name"] = "ch-1"
        fake.create(*RES, "resourceclaims", obj, namespace="default")
        from k8s_dra_driver_gpu_tpu.kubeletplugin.claim import (
            ResourceClaim,
        )

        state.prepare(ResourceClaim.from_dict(
            obj, driver="compute-domain.tpu.dra.dev"))
        node = fake.get("", "v1", "nodes", "cd-node")
        assert node["metadata"]["labels"][NODE_LABEL] == "cd-uid"

        # The gang failed permanently elsewhere: the controller deleted
        # the claim; this surviving node's sweep unwinds.
        fake.delete(*RES, "resourceclaims", "ch-1", namespace="default")
        sweep = CDStateReconciler(state, fake)
        counts = sweep.reconcile_once()
        assert counts["cd_stale_claim"] == 1
        assert state.prepared_claims() == {}
        node = fake.get("", "v1", "nodes", "cd-node")
        assert NODE_LABEL not in node["metadata"].get("labels", {})

    def test_orphan_cd_cdi_spec_unwound(self, tmp_path):
        from k8s_dra_driver_gpu_tpu.computedomain.plugin.device_state \
            import CDDeviceState

        fake = FakeKubeClient()
        fake.create("", "v1", "nodes",
                    {"metadata": {"name": "cd-node", "labels": {}}})
        state = CDDeviceState(root=str(tmp_path / "cd"), kube=fake,
                              node_name="cd-node", use_informer=False)
        # Crash between the CDI write and the single-phase checkpoint
        # write leaves exactly this orphan.
        state._cdi.create_claim_spec_file("ghost", {}, None)
        counts = CDStateReconciler(state, fake).reconcile_once()
        assert counts["cd_cdi_spec"] == 1
        assert state._cdi.list_claim_uids() == []


# -- event-driven integration -------------------------------------------------


class TestEventDrivenRecovery:
    def test_node_kill_converges_through_dirty_keys(self, tmp_path):
        fake = FakeKubeClient()
        apply_class(fake)
        for node in ("node-a", "node-b"):
            add_node(fake, node)
            publish_resource_slices(fake, node_slices(node))
        sched = DraScheduler(fake)
        ctrl = EvictionController(fake, str(tmp_path / "r"),
                                  notready_grace_s=0.0,
                                  deadline_s=60.0)
        sched.attach_recovery(ctrl)
        sched.start_event_driven()
        try:
            assert sched.drain(15.0)
            for i in range(2):
                make_pending_claim(fake, f"c{i}")
                make_pod(fake, f"c{i}-pod", f"c{i}")
            assert sched.drain(15.0)
            placed = {f"c{i}": alloc_node(fake, f"c{i}")
                      for i in range(2)}
            victims = [n for n, nd in placed.items()
                       if nd == "node-b"]
            assert victims
            set_ready(fake, "node-b", False)
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                sched.drain(15.0)
                if all(alloc_node(fake, v) == "node-a"
                       for v in victims) and \
                        not ctrl.active_evictions():
                    break
                time.sleep(0.02)
            for v in victims:
                assert alloc_node(fake, v) == "node-a"
            assert ctrl.active_evictions() == {}
        finally:
            sched.stop()

    def test_excluded_node_never_reallocated_onto(self, cluster):
        """With only failed capacity left, the claim stays pending --
        allocation onto a declared-failed node would re-kill it."""
        fake, sched, ctrl = cluster
        make_pending_claim(fake, "c0", count=4)
        settle(sched, 2)
        victim = alloc_node(fake, "c0")
        survivor = "node-a" if victim == "node-b" else "node-b"
        # Fill the survivor so re-placement has nowhere to go.
        make_pending_claim(fake, "blocker", count=4)
        settle(sched, 2)
        set_ready(fake, victim, False)
        settle(sched)
        assert alloc_node(fake, "c0") is None
        assert condition(fake, "c0")["status"] == "True"


# -- interleaving coverage of the eviction state machine ----------------------


class _YieldingKube:
    """Kube wrapper turning every API verb into an explorer choice
    point, so the DFS permutes a racing actor across every eviction
    stage boundary. No-op passthrough from uninstrumented threads."""

    def __init__(self, sched, inner):
        self._sched = sched
        self._inner = inner

    def _verb(self, name):
        inner = getattr(self._inner, name)

        def call(*a, **kw):
            self._sched.yield_point(f"kube.{name}")
            return inner(*a, **kw)
        return call

    def __getattr__(self, item):
        if item in ("get", "list", "create", "update", "patch",
                    "delete"):
            return self._verb(item)
        return getattr(self._inner, item)


class TestEvictionInterleaveDFS:
    def test_claim_delete_races_every_eviction_stage(
            self, tmp_path, monkeypatch):
        """DFS coverage of the eviction state machine: a user deleting
        the claim is interleaved at EVERY kube-verb boundary of the
        controller's plan -> drain -> deallocate -> retire ladder. All
        schedules must end converged -- no stuck record, no illegal
        transition (a CheckpointTransitionError inside any schedule is
        a finding with a deterministic reproducer)."""
        from k8s_dra_driver_gpu_tpu.pkg.analysis import interleave

        # Consistency here is judged by end-state, not crash
        # durability; stubbing fsync keeps hundreds of schedules fast.
        monkeypatch.setattr(os, "fsync", lambda fd: None)
        monkeypatch.setattr(os, "fdatasync", lambda fd: None)
        runs = [0]

        def build(sched):
            runs[0] += 1
            fake = FakeKubeClient()
            apply_class(fake)
            for node in ("node-a", "node-b"):
                add_node(fake, node)
                publish_resource_slices(fake, node_slices(node))
            make_pending_claim(fake, "c0")
            make_pod(fake, "c0-pod", "c0")
            setup = DraScheduler(fake)
            setup.sync_once()  # main thread: yield points are no-ops
            set_ready(fake, alloc_node(fake, "c0"), False)
            ctrl = EvictionController(
                _YieldingKube(sched, fake),
                str(tmp_path / f"dfs-{runs[0]}"),
                notready_grace_s=0.0, deadline_s=60.0)
            sched.ctrl = ctrl
            sched.fake = fake

            def controller():
                for _ in range(3):
                    ctrl.sync_once()

            def user():
                sched.yield_point("user.delete")
                fake.delete(*RES, "resourceclaims", "c0",
                            namespace="default")

            sched.spawn(controller, "ctrl")
            sched.spawn(user, "user")

        def invariant(sched):
            # Quiesce from the (uninstrumented) main thread: one more
            # sync must retire whatever the schedule left in flight.
            sched.ctrl.sync_once()
            leftover = sched.ctrl.active_evictions()
            assert leftover == {}, f"stuck eviction records: {leftover}"

        result = interleave.explore(build, invariant,
                                    max_schedules=150)
        assert result.schedules_run >= 10
        assert result.ok, f"{len(result.failures)} failing schedule(s);"\
            f" first: {result.failures[0] if result.failures else None}"


# -- metrics ------------------------------------------------------------------


class TestRecoveryMetrics:
    def test_exposition(self, cluster):
        from prometheus_client import generate_latest

        fake, sched, _ = cluster
        metrics = RecoveryMetrics()
        ctrl = EvictionController(
            fake, str(os.path.join(os.environ.get("TMPDIR", "/tmp"),
                                   f"recmetrics-{os.getpid()}")),
            metrics=metrics, notready_grace_s=0.0, deadline_s=60.0)
        sched.attach_recovery(ctrl)
        make_pending_claim(fake, "m0")
        settle(sched, 2)
        set_ready(fake, alloc_node(fake, "m0"), False)
        settle(sched)
        text = generate_latest(metrics.registry).decode()
        assert "tpu_dra_recovery_evictions_total 1.0" in text
        assert "tpu_dra_recovery_replaced_total 1.0" in text
        assert 'tpu_dra_recovery_permanent_failures_total{' \
            'source="node"} 1.0' in text
        assert "tpu_dra_recovery_active_evictions 0.0" in text
