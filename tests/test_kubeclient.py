"""KubeClient tests against a real (local) HTTP API-server stub:
CRUD paths, bearer auth, error mapping, and streamed watch with
reconnect.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from k8s_dra_driver_gpu_tpu.pkg.kubeclient import (
    ConflictError,
    FakeKubeClient,
    KubeClient,
    KubeError,
    NotFoundError,
)


class ApiServerStub(ThreadingHTTPServer):
    """Implements just enough of the REST surface."""

    def __init__(self):
        self.store = {}
        self.raw: dict[str, str] = {}  # path -> text/plain body
        self.watch_events: list[dict] = []
        self.watch_connections = 0
        self.gone_on_rv = False  # reply 410 to watches with resourceVersion
        self.gone_replies = 0
        self.requests: list[tuple[str, str, str]] = []  # method, path, auth
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, code, doc):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                stub.requests.append(
                    ("GET", self.path, self.headers.get("Authorization", ""))
                )
                if "watch=true" in self.path:
                    stub.watch_connections += 1
                    if stub.gone_on_rv and "resourceVersion=" in self.path:
                        stub.gone_replies += 1
                        self._reply(410, {"message": "Expired: too old"})
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                    for ev in stub.watch_events:
                        line = (json.dumps(ev) + "\n").encode()
                        self.wfile.write(
                            f"{len(line):x}\r\n".encode() + line + b"\r\n"
                        )
                        self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                    return
                if self.path in stub.raw:
                    body = stub.raw[self.path].encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path == "/version":
                    self._reply(200, {"major": "1", "minor": "34"})
                    return
                obj = stub.store.get(self.path)
                if obj is None:
                    self._reply(404, {"message": "not found"})
                else:
                    self._reply(200, obj)

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                obj = json.loads(self.rfile.read(length))
                name = obj["metadata"]["name"]
                stub.store[f"{self.path}/{name}"] = obj
                self._reply(201, obj)

            def log_message(self, *args):
                pass

        super().__init__(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.serve_forever, daemon=True)
        self.thread.start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.server_address[1]}"


@pytest.fixture()
def stub():
    s = ApiServerStub()
    yield s
    s.shutdown()
    s.server_close()


class TestKubeClientREST:
    def test_crud_and_auth(self, stub):
        client = KubeClient(host=stub.url, token="sekret")
        obj = {"metadata": {"name": "rs1"}, "spec": {}}
        client.create("resource.k8s.io", "v1", "resourceslices", obj)
        got = client.get("resource.k8s.io", "v1", "resourceslices", "rs1")
        assert got["metadata"]["name"] == "rs1"
        assert stub.requests[-1][2] == "Bearer sekret"
        assert client.server_version()["minor"] == "34"

    def test_not_found_maps(self, stub):
        client = KubeClient(host=stub.url)
        with pytest.raises(NotFoundError):
            client.get("resource.k8s.io", "v1", "resourceslices", "nope")

    def test_no_host_configured(self, monkeypatch):
        monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
        with pytest.raises(KubeError):
            KubeClient()


class TestKubeconfig:
    def test_from_kubeconfig_token_auth(self, stub, tmp_path):
        import yaml

        cfg = {
            "current-context": "e2e",
            "contexts": [{"name": "e2e",
                          "context": {"cluster": "c1", "user": "u1"}}],
            "clusters": [{"name": "c1", "cluster": {"server": stub.url}}],
            "users": [{"name": "u1", "user": {"token": "e2e-token"}}],
        }
        path = tmp_path / "kubeconfig"
        path.write_text(yaml.safe_dump(cfg))
        client = KubeClient.from_kubeconfig(str(path))
        assert client.server_version()["major"] == "1"
        # The bearer token from the kubeconfig rode the request.
        assert any(a == "Bearer e2e-token" for _, _, a in stub.requests)

    def test_read_raw_returns_plain_text(self, stub):
        stub.raw["/api/v1/namespaces/ns/pods/p/log"] = "line1\nline2\n"
        client = KubeClient(host=stub.url)
        body = client.read_raw("/api/v1/namespaces/ns/pods/p/log")
        assert body == "line1\nline2\n"

    def test_read_raw_404_maps_to_not_found(self, stub):
        client = KubeClient(host=stub.url)
        with pytest.raises(NotFoundError):
            client.read_raw("/api/v1/namespaces/ns/pods/gone/log")

    def test_fake_read_raw_same_surface(self):
        from k8s_dra_driver_gpu_tpu.pkg.kubeclient import FakeKubeClient

        kube = FakeKubeClient()
        kube.create("", "v1", "pods", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p", "namespace": "ns",
                         "annotations": {"fake/log": "hello"}},
        }, namespace="ns")
        assert kube.read_raw("/api/v1/namespaces/ns/pods/p/log") == "hello"
        with pytest.raises(NotFoundError):
            kube.read_raw("/api/v1/namespaces/ns/pods/gone/log")


class TestKubeClientWatch:
    def test_watch_streams_and_reconnects(self, stub):
        stub.watch_events = [
            {"type": "ADDED", "object": {
                "kind": "ComputeDomain",
                "metadata": {"name": "cd1", "resourceVersion": "5"}}},
            {"type": "BOOKMARK", "object": {
                "metadata": {"resourceVersion": "6"}}},
            {"type": "MODIFIED", "object": {
                "kind": "ComputeDomain",
                "metadata": {"name": "cd1", "resourceVersion": "7"}}},
        ]
        client = KubeClient(host=stub.url)
        seen = []
        stop = threading.Event()
        client.watch(
            "resource.tpu.dra", "v1beta1", "computedomains",
            lambda t, o: seen.append((t, o["metadata"]["name"])),
            stop=stop, reconnect_delay=0.2,
        )
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(seen) < 2:
            time.sleep(0.05)
        stop.set()
        assert ("ADDED", "cd1") in seen
        assert ("MODIFIED", "cd1") in seen
        # BOOKMARK events are swallowed.
        assert all(t != "BOOKMARK" for t, _ in seen)

    def test_watch_reconnects_after_stream_end(self, stub):
        stub.watch_events = [
            {"type": "ADDED", "object": {
                "metadata": {"name": "x", "resourceVersion": "1"}}},
        ]
        client = KubeClient(host=stub.url)
        stop = threading.Event()
        client.watch(
            "resource.tpu.dra", "v1beta1", "computedomains",
            lambda t, o: None, stop=stop, reconnect_delay=0.1,
        )
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and stub.watch_connections < 2:
            time.sleep(0.05)
        stop.set()
        # The stream ended and the client dialed again with the bookmark.
        assert stub.watch_connections >= 2
        watch_paths = [p for m, p, _ in stub.requests if "watch=true" in p]
        assert any("resourceVersion=1" in p for p in watch_paths)

    def test_watch_410_resets_resource_version(self, stub):
        # An HTTP-level 410 Gone at watch establishment (expired
        # resourceVersion after a long disconnect) must reset the
        # bookmark instead of redialing with the stale version forever.
        stub.watch_events = [
            {"type": "ADDED", "object": {
                "metadata": {"name": "x", "resourceVersion": "1"}}},
        ]
        stub.gone_on_rv = True
        client = KubeClient(host=stub.url)
        stop = threading.Event()
        client.watch(
            "resource.tpu.dra", "v1beta1", "computedomains",
            lambda t, o: None, stop=stop, reconnect_delay=0.05,
        )
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and stub.watch_connections < 3:
            time.sleep(0.05)
        stop.set()
        assert stub.gone_replies >= 1
        # After the 410 the client redialed WITHOUT a resourceVersion.
        watch_paths = [p for m, p, _ in stub.requests if "watch=true" in p]
        post_gone = [p for p in watch_paths[1:] if "resourceVersion=" not in p]
        assert post_gone


class TestOptimisticConcurrency:
    def test_stale_resource_version_conflicts(self):
        kube = FakeKubeClient()
        kube.create("", "v1", "configmaps", {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "cm"}, "data": {"k": "0"},
        }, namespace="ns")
        first = kube.get("", "v1", "configmaps", "cm", namespace="ns")
        second = kube.get("", "v1", "configmaps", "cm", namespace="ns")
        first["data"]["k"] = "1"
        kube.update("", "v1", "configmaps", "cm", first, namespace="ns")
        # Writer 2 holds the old resourceVersion: lost-update prevented.
        second["data"]["k"] = "2"
        with pytest.raises(ConflictError):
            kube.update("", "v1", "configmaps", "cm", second,
                        namespace="ns")
        assert kube.get("", "v1", "configmaps", "cm",
                        namespace="ns")["data"]["k"] == "1"
        # An rv-less update is accepted (k8s semantics).
        kube.update("", "v1", "configmaps", "cm", {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "cm"}, "data": {"k": "3"},
        }, namespace="ns")
        assert kube.get("", "v1", "configmaps", "cm",
                        namespace="ns")["data"]["k"] == "3"

    def test_patch_rv_in_body_is_a_precondition(self):
        kube = FakeKubeClient()
        kube.create("", "v1", "configmaps", {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "cm"}, "data": {"k": "0"},
        }, namespace="ns")
        stale = kube.get("", "v1", "configmaps", "cm", namespace="ns")
        for i in range(3):  # advance the stored rv well past the copy
            kube.patch("", "v1", "configmaps", "cm",
                       {"data": {"k": str(i)}}, namespace="ns")
        # A resourceVersion inside a merge-patch body is an optimistic
        # concurrency precondition (real apiserver semantics): stale rv
        # is a 409, never a silent rewind of the counter.
        stale["data"]["k"] = "stale"
        with pytest.raises(ConflictError):
            kube.patch("", "v1", "configmaps", "cm", stale, namespace="ns")
        fresh = kube.get("", "v1", "configmaps", "cm", namespace="ns")
        assert fresh["data"]["k"] == "2"
        assert int(fresh["metadata"]["resourceVersion"]) >= 4
        # A MATCHING rv in the body applies, bumps, and never rewinds.
        fresh["data"]["k"] = "after"
        out = kube.patch("", "v1", "configmaps", "cm", fresh,
                         namespace="ns")
        assert out["data"]["k"] == "after"
        assert (int(out["metadata"]["resourceVersion"])
                > int(fresh["metadata"]["resourceVersion"]))
