"""Tier-1 multi-tenant serving smoke: the `make bench-serving-smoke`
contract as a non-slow test. Runs `bench.py --serving` at reduced scale
and asserts the partition-engine gate set: tenant density >= 4x the
whole-chip baseline, ZERO counter over-commit, every active tenant
converged, bounded carve-out create p99, zero-write converged
republishes, and idempotent resume of the partition create/destroy
crash points -- so a regression anywhere in the pkg/partition stack
(sizing, slot-aware allocation, engine lifecycle, counter scaling)
fails fast here instead of surfacing as a BENCH trajectory dip."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Keep in sync with the Makefile bench-serving-smoke target.
SMOKE_ENV = {
    "BENCH_SERVING_NODES": "4",
    "BENCH_SERVING_TENANTS": "96",
    "BENCH_SERVING_BURST": "24",
    "BENCH_SERVING_ROUNDS": "3",
}


def test_serving_smoke(tmp_path):
    out_file = str(tmp_path / "BENCH_serving.json")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--serving"],
        env={**os.environ, "PYTHONPATH": REPO, **SMOKE_ENV,
             "BENCH_SERVING_OUT": out_file},
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["metric"] == "serving_tenants_per_chip"
    ex = doc["extras"]
    # The headline: >= 4x tenants per chip vs the whole-chip baseline
    # (MISO sizing picked an 8-slot profile for the ~2Gi demand, so
    # the fleet lands well above the floor even under churn).
    assert doc["vs_baseline"] >= 4.0
    assert ex["serving_profile_slots"] >= 4
    # Zero counter over-commit, recomputed from the final allocations.
    assert ex["serving_serving_overcommitted_counters"] == 0
    assert ex["serving_baseline_overcommitted_counters"] == 0
    # Every active tenant converged (capacity covers the active set).
    assert ex["serving_serving_pending"] == 0
    assert ex["serving_serving_active"] > ex["serving_baseline_active"]
    # Converged republish through the content-hash diff: zero writes.
    assert ex["serving_serving_republish_writes"] == 0
    # Real-node carve-out creation stayed within the latency budget.
    assert ex["serving_create_p99_ms"] is not None
    assert ex["serving_create_p99_ms"] <= 1000.0
    # Crash points (mid-create / mid-destroy) resumed idempotently
    # under a fresh plugin on the same state root.
    assert ex["serving_crash_create_resumed"] is True
    assert ex["serving_crash_destroy_resumed"] is True
    # The trajectory artifact landed and round-trips.
    with open(out_file, encoding="utf-8") as f:
        emitted = json.load(f)
    assert emitted["vs_baseline"] == doc["vs_baseline"]
    # The ParvaGPU packing plan agrees with the realized density to
    # within churn (the plan has no churn, so it upper-bounds).
    assert ex["serving_pack_tenants_per_chip"] >= doc["value"]
