"""Tier-1 migration smoke: the `make bench-migration-smoke` contract
as a non-slow test. Runs bench.py --migration at reduced scale and
asserts the cooperative live-migration acceptance bar: the training
gang migrates off the evacuating host with bounded step-loss and a
warm checkpoint restore, the serving tenant resizes s8->s2 with zero
dropped requests, every fault case (4 crash seams, ack-timeout,
checkpoint-failed, destination-lost, racing-delete) resumes or falls
back cold with zero stuck claims / leaked reservations / leftover
contract annotations, and the cooperative cost tier visibly discounts
defrag victim costs on identical pools -- plus the
BENCH_migration.json trajectory file actually written."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Keep in sync with the Makefile bench-migration-smoke target.
SMOKE_ENV = {
    "BENCH_MIGRATION_PASSES": "24",
    "BENCH_MIGRATION_REQUESTS_PER_PASS": "3",
}


def test_bench_migration_smoke_moves_warm_and_falls_back_cold(tmp_path):
    out_json = tmp_path / "BENCH_migration.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--migration"],
        env={**os.environ, "PYTHONPATH": REPO, **SMOKE_ENV,
             "BENCH_MIGRATION_OUT": str(out_json)},
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["metric"] == "migration_violations"
    # THE acceptance bar: zero violations of any kind.
    assert doc["value"] == 0
    extras = doc["extras"]

    # Training gang: both members moved cooperatively (zero cold
    # fallbacks) with bounded step-loss and an intact warm restore.
    assert extras["migration_train_coop_moves"] == 2
    assert extras["migration_train_fallbacks"] == 0
    assert extras["migration_train_warm_restore_ok"] == 1
    assert extras["migration_train_step_loss"] <= 5
    # The cooperative checkpoint-on-demand must beat (or match) the
    # periodic-checkpoint cold counterfactual.
    assert extras["migration_train_step_loss"] <= \
        extras["migration_train_cold_step_loss_counterfactual"]

    # Serving resize s8 -> s2: make-before-break, zero drops.
    assert extras["migration_serving_dropped"] == 0
    assert extras["migration_serving_resize_done"] == 1
    assert extras["migration_serving_final_chips"] == 2
    assert extras["migration_serving_coop_moves"] >= 1

    # Every fault case landed on its contract: crash seams resume,
    # non-crash faults fall back cold, a racing delete cancels.
    sweep = extras["migration_fault_sweep"]
    for case in ("crash-sync", "crash-reserve", "crash-signal",
                 "crash-switch"):
        assert sweep[case] == "resumed", (case, sweep)
    assert sweep["ack-timeout"] == "fellback:ack-timeout"
    assert sweep["checkpoint-failed"] == "fellback:checkpoint-failed"
    assert sweep["racing-delete"] == "canceled"
    assert sweep["destination-lost"].startswith("fellback:")

    # The cooperative tier visibly discounts the SAME defrag victims.
    assert extras["migration_defrag_cold_victims"] == \
        extras["migration_defrag_coop_victims"]
    assert extras["migration_defrag_cost_ratio"] is not None
    assert extras["migration_defrag_cost_ratio"] <= 0.5

    # The trajectory file landed.
    recorded = json.loads(out_json.read_text())
    assert recorded["metric"] == "migration_violations"
    assert recorded["trajectory"]
