"""Multi-actor protocol model checker tests (pkg/analysis/modelcheck).

Three layers:
- unit tests over the modeled apiserver / informer / durable
  checkpoint (the semantics every scenario leans on);
- the seeded-bug self-test: with the resourceVersion precondition
  removed, bounded DFS must catch the double-allocation, minimize it,
  and replay it deterministically -- mirroring `make modelcheck-smoke`;
- bounded correct-protocol sweeps over the commit / prepare / recovery
  scenarios (the full >= 10k-schedule run is `make modelcheck`).
"""

import json
import os
import subprocess
import sys

import pytest

from k8s_dra_driver_gpu_tpu.pkg.analysis.interleave import (
    ReplayChooser,
    _run_one,
    explore,
    explore_random,
)
from k8s_dra_driver_gpu_tpu.pkg.analysis.modelcheck import (
    CommitScenario,
    DurableCheckpoint,
    ModelApiServer,
    ModelInformer,
    check_scenario,
    check_seeded_bug,
    independent_ops,
    make_artifact,
    minimize_failure,
    replay_artifact,
    run_gates,
)
from k8s_dra_driver_gpu_tpu.pkg.analysis.statemachine import (
    EVICTION_POLICY,
    PREPARE_COMPLETED,
    PREPARE_STARTED,
    TWO_PHASE_POLICY,
    CheckpointTransitionError,
)
from k8s_dra_driver_gpu_tpu.pkg.kubeclient import (
    ConflictError,
    NotFoundError,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestModelApiServer:
    def mk(self):
        return ModelApiServer({
            "ledger": {"spec": {"devices": {"d0": None}}},
            "c0": {"metadata": {"uid": "u0"}, "status": {}},
        })

    def test_objects_get_monotonic_resource_versions(self):
        api = self.mk()
        rvs = [int(api.get(n)["metadata"]["resourceVersion"])
               for n in api.names()]
        assert len(set(rvs)) == len(rvs)
        before = int(api.get("c0")["metadata"]["resourceVersion"])
        api.patch("c0", {"status": {"x": 1}})
        assert int(api.get("c0")["metadata"]["resourceVersion"]) > before

    def test_update_rv_precondition_conflicts(self):
        api = self.mk()
        stale = api.get("ledger")
        api.patch("ledger", {"spec": {"devices": {"d0": "c0"}}})
        with pytest.raises(ConflictError):
            api.update("ledger", stale)
        # The losing write changed nothing.
        assert api.get("ledger")["spec"]["devices"]["d0"] == "c0"
        # A fresh read's rv wins.
        fresh = api.get("ledger")
        fresh["spec"]["devices"]["d0"] = "c1"
        api.update("ledger", fresh)
        assert api.get("ledger")["spec"]["devices"]["d0"] == "c1"

    def test_patch_rv_in_body_is_a_precondition(self):
        api = self.mk()
        stale_rv = api.get("c0")["metadata"]["resourceVersion"]
        api.patch("c0", {"status": {"x": 1}})
        with pytest.raises(ConflictError):
            api.patch("c0", {"metadata": {"resourceVersion": stale_rv},
                             "status": {"x": 2}})
        assert api.get("c0")["status"]["x"] == 1

    def test_rv_less_patch_is_the_blind_merge(self):
        # Exactly the seeded bug's weapon: last writer silently wins.
        api = self.mk()
        api.patch("ledger", {"spec": {"devices": {"d0": "c0"}}})
        api.patch("ledger", {"spec": {"devices": {"d0": "c1"}}})
        assert api.get("ledger")["spec"]["devices"]["d0"] == "c1"

    def test_merge_none_deletes_and_get_is_a_copy(self):
        api = self.mk()
        api.patch("c0", {"status": {"x": 1}})
        api.patch("c0", {"status": {"x": None}})
        assert "x" not in api.get("c0")["status"]
        api.get("c0")["status"]["evil"] = True
        assert "evil" not in api.get("c0")["status"]
        with pytest.raises(NotFoundError):
            api.get("nope")
        with pytest.raises(NotFoundError):
            api.patch("nope", {})

    def test_subscribers_see_every_committed_write(self):
        api = self.mk()
        inf = ModelInformer(api, "s0")
        assert inf.deliver() == 2  # primed with the initial list
        api.patch("ledger", {"spec": {"devices": {"d0": "c0"}}})
        api.patch("c0", {"status": {"x": 1}})
        assert len(inf.queue) == 2
        # Partial delivery models informer lag: the tail stays queued.
        assert inf.deliver(upto=1) == 1
        assert inf.get("ledger")["spec"]["devices"]["d0"] == "c0"
        assert inf.get("c0")["status"] == {}
        inf.deliver()
        assert inf.get("c0")["status"]["x"] == 1


class TestDurableCheckpoint:
    def test_transitions_validated_by_policy(self):
        cp = DurableCheckpoint(TWO_PHASE_POLICY)
        cp.transition("u", PREPARE_STARTED)
        cp.transition("u", PREPARE_COMPLETED)
        cp.transition("u", None)
        assert cp.states == {}

    def test_illegal_transition_rejected(self):
        cp = DurableCheckpoint(TWO_PHASE_POLICY)
        with pytest.raises(CheckpointTransitionError):
            cp.transition("u", PREPARE_COMPLETED)  # skipped reservation
        assert cp.states == {}

    def test_eviction_policy_wired(self):
        cp = DurableCheckpoint(EVICTION_POLICY)
        with pytest.raises(CheckpointTransitionError):
            cp.transition("u", "EvictionDeallocated")


class TestIndependenceJudgment:
    def test_cross_actor_writes_to_distinct_objects_commute(self):
        assert independent_ops("s0:write ledger", "s1:write c0")

    def test_same_object_writes_dependent(self):
        assert not independent_ops("s0:write ledger", "s1:write ledger")

    def test_same_actor_never_commutes(self):
        assert not independent_ops("s0:write ledger", "s0:write c0")

    def test_reads_always_commute_cross_actor(self):
        assert independent_ops("s0:read ledger", "s1:read ledger")
        assert not independent_ops("s0:read ledger", "s1:write ledger")

    def test_deliveries_crashes_and_unparsable_dependent(self):
        assert not independent_ops("s0:deliver[1]", "s1:write c0")
        assert not independent_ops("s0:crash@pre-reserve[0]",
                                   "s1:write c0")
        assert not independent_ops("start s0", "s1:write c0")


class TestSeededBugGate:
    """The CI-mirror: the deliberately re-seeded blind-write bug
    (precondition=False, i.e. TPUDRA018's defect) must be caught,
    minimized, and deterministically replayable within the smoke
    budget."""

    def test_seeded_double_allocation_caught_and_replayable(self):
        out = check_seeded_bug(max_schedules=400)
        assert out["caught"], "seeded bug escaped the bounded DFS"
        assert out["replay_deterministic"]
        assert out["ok"]
        assert out["schedules_run"] <= 400
        # Minimization reached a small reproducer.
        assert 0 < len(out["minimized_choices"]) <= 12
        # The artifact round-trips through the replay entrypoint.
        sched, err = replay_artifact(out["artifact"])
        assert err is not None
        assert type(err).__name__ == out["artifact"]["error_type"]

    def test_minimized_schedule_is_no_longer_failing_when_fixed(self):
        # Replaying the buggy schedule against the CORRECT protocol
        # must pass: the failure is the protocol's, not the harness's.
        out = check_seeded_bug(max_schedules=400)
        artifact = dict(out["artifact"],
                        params={"precondition": True, "crashes": 0})
        sched, err = replay_artifact(artifact)
        assert err is None

    def test_minimize_only_shrinks_and_stays_failing(self):
        scenario = CommitScenario(precondition=False)
        res = explore(scenario.build, scenario.invariant,
                      max_schedules=400, stop_at_first_failure=True,
                      independent=independent_ops)
        failure = res.failures[0]
        error_type = type(failure.error).__name__
        minimized, probes = minimize_failure(
            scenario, failure.choices, error_type)
        assert len(minimized) <= len(failure.choices)
        assert probes > 0
        _, err = _run_one(scenario.build, scenario.invariant,
                          ReplayChooser(minimized))
        assert err is not None and type(err).__name__ == error_type


class TestCorrectProtocolScenarios:
    """Bounded clean sweeps (the full budget lives in
    `make modelcheck`): the rv-preconditioned protocol survives DFS +
    seeded-random exploration, including crash schedules."""

    def test_commit_no_crashes_clean(self):
        scenario = CommitScenario(precondition=True)
        res = explore(scenario.build, scenario.invariant,
                      max_schedules=250, independent=independent_ops)
        assert res.ok, "\n".join(str(f) for f in res.failures[:3])
        scenario = CommitScenario(precondition=True)
        rres = explore_random(scenario.build, scenario.invariant,
                              schedules=150, seed=11)
        assert rres.ok, "\n".join(str(f) for f in rres.failures[:3])

    @pytest.mark.parametrize("name", ["commit", "prepare", "recovery"])
    def test_scenario_with_crash_budget_clean(self, name):
        out = check_scenario(name, dfs=120, rand=60, seed=5, crashes=1)
        assert out["ok"], out["failures"]
        assert out["schedules_run"] > 0


class TestGateRunner:
    def test_run_gates_smoke_mirror(self):
        """Tier-1 mirror of the `make modelcheck-smoke` CI step, at a
        reduced budget: every gate (seeded bug, three scenarios, crash
        closure) must pass."""
        report = run_gates(full=False, schedules=240)
        assert report["ok"], report
        assert report["mode"] == "smoke"
        gates = {g["gate"]: g for g in report["gates"]}
        assert gates["seeded-bug"]["caught"]
        assert gates["crash-closure"]["ok"]
        assert {"commit(crashes=0)", "commit(crashes=1)",
                "prepare(crashes=1)",
                "recovery(crashes=1)"} <= set(gates)
        assert report["schedules_total"] > 0

    @pytest.mark.slow
    def test_cli_smoke_passes(self, tmp_path):
        out_path = tmp_path / "report.json"
        proc = subprocess.run(
            [sys.executable, "-m",
             "k8s_dra_driver_gpu_tpu.pkg.analysis.modelcheck",
             "--smoke", "--json-out", str(out_path)],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "PYTHONPATH": REPO,
                 "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "PASS" in proc.stdout
        report = json.loads(out_path.read_text())
        assert report["ok"] and report["mode"] == "smoke"

    def test_replay_cli_reproduces_artifact(self, tmp_path):
        out = check_seeded_bug(max_schedules=400)
        artifact_path = tmp_path / "artifact.json"
        artifact_path.write_text(json.dumps(out["artifact"]))
        proc = subprocess.run(
            [sys.executable, "-m",
             "k8s_dra_driver_gpu_tpu.pkg.analysis.modelcheck",
             "--replay", str(artifact_path)],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "PYTHONPATH": REPO,
                 "JAX_PLATFORMS": "cpu"},
        )
        # Exit 1 = the recorded schedule still reproduces the failure.
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "replay reproduces" in proc.stdout


class TestArtifactShape:
    def test_make_artifact_records_scenario_and_params(self):
        scenario = CommitScenario(precondition=False, crashes=0)
        res = explore(scenario.build, scenario.invariant,
                      max_schedules=400, stop_at_first_failure=True,
                      independent=independent_ops)
        artifact = make_artifact(scenario, res.failures[0])
        assert artifact["scenario"] == "commit"
        assert artifact["params"] == {"precondition": False, "crashes": 0}
        assert artifact["choices"] == res.failures[0].choices
        assert artifact["error_type"]
        assert json.loads(json.dumps(artifact)) == artifact
