"""Concurrent claim-prepare pipeline tests.

Covers the sharded-locking redesign of DeviceState.prepare() and the
group-committed CheckpointManager:

- disjoint claims prepare in overlapping wall-clock time (the node
  flock + process lock now guard only the reservation section);
- a thread barrier hammering prepare/unprepare churn leaves a
  consistent, checksum-verifiable checkpoint;
- concurrent committers share fsyncs (group commit);
- a failed flush poisons the read cache instead of serving
  never-persisted mutations;
- a SIGKILL mid-prepare with the coalesced writer still recovers via
  the PrepareStarted rollback on the next attempt.
"""

import concurrent.futures
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from k8s_dra_driver_gpu_tpu.kubeletplugin.checkpoint import (
    Checkpoint,
    CheckpointedClaim,
    CheckpointManager,
    ClaimState,
)
from k8s_dra_driver_gpu_tpu.kubeletplugin.device_state import (
    Config,
    DeviceState,
)
from tests.fake_kube import make_claim

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ, "PYTHONPATH": REPO}


@pytest.fixture()
def state(tmp_root):
    return DeviceState(Config.mock(root=tmp_root, topology="v5e-4"))


class TestDisjointPreparesOverlap:
    def test_stalled_middles_run_concurrently(self, state, monkeypatch):
        """3 disjoint claims, each stalled 1.2s inside prep_devices
        (outside the global lock): serialized execution would take
        >= 3.6s, the sharded pipeline takes ~one stall (the generous
        margin absorbs the multi-second fsync hiccups BASELINE.md
        documents for CI boxes)."""
        monkeypatch.setenv("TPU_DRA_STALL_AT_SEGMENT", "prep_devices")
        monkeypatch.setenv("TPU_DRA_STALL_SECONDS", "1.2")
        chips = ["chip-0", "chip-1", "chip-2"]
        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(len(chips)) as ex:
            results = list(ex.map(
                lambda c: state.prepare(make_claim(f"ov-{c}", [c])), chips,
            ))
        wall = time.perf_counter() - t0
        assert all(len(ids) == 1 for ids in results)
        assert wall < 3.0, (
            f"{wall:.2f}s wall for 3 x 1.2s-stalled prepares: the "
            "expensive middle serialized"
        )
        for c in chips:
            claim = state.prepared_claims()[f"ov-{c}"]
            assert claim.state == ClaimState.PREPARE_COMPLETED.value

    def test_same_chip_claims_overlap_rejected_not_raced(
        self, state, monkeypatch
    ):
        """While a claim's middle is stalled its reservation is already
        durable: a concurrent overlapping prepare fails validation
        instead of double-allocating the chip."""
        monkeypatch.setenv("TPU_DRA_STALL_AT_SEGMENT", "prep_devices")
        monkeypatch.setenv("TPU_DRA_STALL_SECONDS", "0.5")
        errors = []

        def racer(uid):
            try:
                state.prepare(make_claim(uid, ["chip-0"]))
            except Exception as e:  # noqa: BLE001
                errors.append(str(e))

        threads = [threading.Thread(target=racer, args=(f"race-{i}",))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # Exactly one winner; the loser saw the winner's reservation.
        assert len(errors) == 1, errors
        assert "overlap" in errors[0]
        assert sum(
            1 for c in state.prepared_claims().values()
            if c.state == ClaimState.PREPARE_COMPLETED.value
        ) == 1


class TestChurnConsistency:
    def test_barrier_churn_leaves_consistent_checkpoint(self, tmp_root):
        state = DeviceState(Config.mock(root=tmp_root, topology="v5e-4"))
        workers, iters = 4, 6
        barrier = threading.Barrier(workers)
        failures = []

        def worker(wid):
            chip = f"chip-{wid}"
            barrier.wait(timeout=30)
            try:
                for i in range(iters):
                    uid = f"churn-{wid}-{i}"
                    state.prepare(make_claim(uid, [chip]))
                    state.unprepare(uid)
            except Exception as e:  # noqa: BLE001
                failures.append(f"w{wid}: {e}")

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not failures, failures
        # The on-disk file parses AND checksum-verifies in a fresh
        # manager (from_dict raises CheckpointCorruptError otherwise).
        fresh = CheckpointManager(tmp_root)
        assert fresh.get().claims == {}
        # No leaked side state.
        reg = os.path.join(tmp_root, "subslices.json")
        if os.path.exists(reg):
            assert json.load(open(reg)) == {}


class TestGroupCommit:
    def test_concurrent_committers_share_fsyncs(self, tmp_root):
        cm = CheckpointManager(tmp_root, boot_id="boot-1")
        writes = []
        orig = cm._write_locked

        def slow_write(cp):
            writes.append(len(cp.claims))
            time.sleep(0.05)
            orig(cp)

        cm._write_locked = slow_write
        n = 8
        with concurrent.futures.ThreadPoolExecutor(n) as ex:
            list(ex.map(
                lambda i: cm.update_claim(
                    f"gc-{i}",
                    CheckpointedClaim(
                        uid=f"gc-{i}",
                        state=ClaimState.PREPARE_STARTED.value),
                ),
                range(n),
            ))
        assert len(cm.get().claims) == n
        # One committer flushes while the rest enqueue: far fewer
        # write+fsync cycles than committers (worst-case margin: the
        # first flush covers >= 1, every later flush drains the queue).
        assert len(writes) < n, f"{len(writes)} writes for {n} committers"
        # And the coalesced file still checksum-verifies.
        assert len(CheckpointManager(tmp_root, boot_id="boot-1")
                   .get().claims) == n

    def test_fragment_cache_matches_full_reencode(self, tmp_root):
        """The fragment-assembled writer must stay byte-compatible with
        the canonical json.dumps encoding the checksum verifier
        re-marshals on read -- including claim removal and legacy
        update() mutations."""
        cm = CheckpointManager(tmp_root, boot_id="boot-1")
        from k8s_dra_driver_gpu_tpu.kubeletplugin.checkpoint import (
            CheckpointedDevice,
        )
        for i in range(4):
            cm.update_claim(f"frag-{i}", CheckpointedClaim(
                uid=f"frag-{i}", namespace="ns", name=f"n{i}",
                state=ClaimState.PREPARE_COMPLETED.value,
                devices=[CheckpointedDevice(
                    canonical_name=f"chip-{i}", kind="chip",
                    cdi_device_ids=[f"k8s.tpu.dra.dev/claim=chip-{i}"],
                )],
            ))
        cm.update_claim("frag-1", None)
        cm.update(lambda c: c.claims.__setitem__("extra", CheckpointedClaim(
            uid="extra", state=ClaimState.PREPARE_STARTED.value)))
        on_disk = json.load(open(cm.path))
        expected = Checkpoint.from_dict(on_disk)  # checksum-verifies
        assert set(expected.claims) == {"frag-0", "frag-2", "frag-3",
                                        "extra"}
        assert on_disk["checksums"] == Checkpoint(
            node_boot_id=expected.node_boot_id, claims=expected.claims,
        ).to_dict()["checksums"]

    def test_failed_flush_poisons_cache_not_state(self, tmp_root):
        cm = CheckpointManager(tmp_root, boot_id="boot-1")
        cm.update_claim("keep", CheckpointedClaim(
            uid="keep", state=ClaimState.PREPARE_STARTED.value))
        orig = cm._write_locked
        cm._write_locked = lambda cp: (_ for _ in ()).throw(
            OSError("disk full"))
        with pytest.raises(RuntimeError):
            cm.update_claim("lost", CheckpointedClaim(
                uid="lost", state=ClaimState.PREPARE_STARTED.value))
        cm._write_locked = orig
        # The never-persisted mutation must not surface from the cache.
        assert set(cm.get().claims) == {"keep"}
        cm.update_claim("after", CheckpointedClaim(
            uid="after", state=ClaimState.PREPARE_STARTED.value))
        assert set(cm.get().claims) == {"keep", "after"}


class TestCrashRecoveryWithCoalescedWriter:
    def test_kill_mid_prepare_reconciles_and_rolls_back(self, tmp_path):
        """SIGKILL inside prep_devices (reservation durable, device
        mutation in flight, group-commit writer active): a fresh
        DeviceState sees the PrepareStarted reservation -- with its
        device list -- and the retried prepare rolls it back and
        completes."""
        root = tmp_path / "root"
        crashed = subprocess.run(
            [sys.executable, "-m", "tests.prepare_helper",
             str(root), "crash-1", "AUTO_SUBSLICE"],
            env={**ENV, "TPU_DRA_CRASH_AT_SEGMENT": "prep_devices"},
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert crashed.returncode == 86, crashed.stdout + crashed.stderr
        on_disk = json.load(open(root / "checkpoint.json"))
        rec = on_disk["data"]["claims"]["crash-1"]
        assert rec["state"] == ClaimState.PREPARE_STARTED.value
        assert rec["devices"], "reservation must carry the device names"

        state = DeviceState(Config.mock(root=str(root), topology="v5e-4"))
        device = rec["devices"][0]["canonicalName"]
        ids = state.prepare(make_claim("crash-1", [device]))
        assert len(ids) == 1
        assert state.prepared_claims()["crash-1"].state == \
            ClaimState.PREPARE_COMPLETED.value
        state.unprepare("crash-1")
        assert "crash-1" not in state.prepared_claims()

    def test_kill_inside_reservation_section(self, tmp_path):
        """SIGKILL at the prep_reserved seam (global lock held, record
        durable): the kernel releases the flock with the process and the
        stale reservation rolls back on retry."""
        root = tmp_path / "root"
        crashed = subprocess.run(
            [sys.executable, "-m", "tests.prepare_helper",
             str(root), "crash-2", "chip-0", "prepare"],
            env={**ENV, "TPU_DRA_CRASH_AT_SEGMENT": "prep_reserved"},
            capture_output=True, text=True, timeout=60, cwd=REPO,
        )
        assert crashed.returncode == 86, crashed.stdout + crashed.stderr
        state = DeviceState(Config.mock(root=str(root), topology="v5e-4"))
        ids = state.prepare(make_claim("crash-2", ["chip-0"]))
        assert len(ids) == 1


class TestForeignOwnerLease:
    def test_live_peer_reservation_not_rolled_back(self, tmp_path):
        """Handover window: while ANOTHER plugin process's prepare is
        mid-middle (alive, stalled in prep_devices), a retry of the
        same claim in this process must fail retriable -- NOT roll back
        the peer's reservation and race its device mutations. Once the
        peer dies, the stale reservation rolls back normally."""
        root = tmp_path / "root"
        root.mkdir()
        # Init the root first so the in-process DeviceState below
        # doesn't race the helper's own initialization.
        seed = DeviceState(Config.mock(root=str(root), topology="v5e-4"))
        old = subprocess.Popen(
            [sys.executable, "-m", "tests.prepare_helper",
             str(root), "lease-1", "chip-0"],
            env={**ENV, "TPU_DRA_STALL_AT_SEGMENT": "prep_devices",
                 "TPU_DRA_STALL_SECONDS": "60"},
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        try:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                rec = seed.prepared_claims().get("lease-1")
                if rec is not None:
                    break
                time.sleep(0.05)
            assert rec is not None, "helper never wrote its reservation"
            lease = json.load(open(root / "leases" / "lease-1.json"))
            assert lease["pid"] == old.pid and lease["start"] > 0
            from k8s_dra_driver_gpu_tpu.kubeletplugin.device_state import (
                PrepareError,
            )
            with pytest.raises(PrepareError, match="in progress"):
                seed.prepare(make_claim("lease-1", ["chip-0"]))
            with pytest.raises(PrepareError, match="in progress"):
                seed.unprepare("lease-1")
            # Startup-style sweeps also respect the live peer: the
            # unknown-state teardown defers instead of destroying
            # carve-outs the peer may be mid-creating.
            assert seed._live_foreign_reservations() == {"lease-1"}
            assert seed.destroy_unknown_subslices() == 0
        finally:
            old.kill()
            old.wait()
        # Peer dead: the reservation is stale and the retry recovers.
        ids = seed.prepare(make_claim("lease-1", ["chip-0"]))
        assert len(ids) == 1
        seed.unprepare("lease-1")
        assert "lease-1" not in seed.prepared_claims()


class TestInjectedCrashRecovery:
    """pkg/faults crash points through the two-phase pipeline: an
    InjectedCrash (BaseException -- wire boundaries can't swallow it)
    fired at a precise seam, then a FRESH DeviceState over the same
    root must reconcile back to a consistent, claimable state."""

    @pytest.fixture(autouse=True)
    def clean_faults(self):
        from k8s_dra_driver_gpu_tpu.pkg import faults

        faults.reset()
        yield
        faults.reset()

    def test_crash_between_started_and_completed(self, tmp_root):
        """InjectedCrash inside the reservation section, right after
        the durable PrepareStarted write: the reservation (with its
        device list) survives on disk, the 'restarted' plugin treats
        the dead owner's record as stale, rolls it back, and the
        retried prepare completes."""
        from k8s_dra_driver_gpu_tpu.pkg import faults
        from k8s_dra_driver_gpu_tpu.pkg.faults import InjectedCrash

        state = DeviceState(Config.mock(root=tmp_root, topology="v5e-4"))
        with faults.inject("segment:prep_reserved", mode="crash"):
            with pytest.raises(InjectedCrash):
                state.prepare(make_claim("icrash-1", ["chip-0"]))
        # The reservation is durable and carries the device names.
        on_disk = json.load(open(os.path.join(tmp_root, "checkpoint.json")))
        rec = on_disk["data"]["claims"]["icrash-1"]
        assert rec["state"] == ClaimState.PREPARE_STARTED.value
        assert rec["devices"][0]["canonicalName"] == "chip-0"

        # "Restart": a fresh DeviceState over the same root. The
        # startup sweep runs clean (no live peer -- the lease's pid is
        # OUR dead-prepare pid) and the retry rolls back + completes.
        fresh = DeviceState(Config.mock(root=tmp_root, topology="v5e-4"))
        assert fresh.destroy_unknown_subslices() == 0
        ids = fresh.prepare(make_claim("icrash-1", ["chip-0"]))
        assert len(ids) == 1
        assert fresh.prepared_claims()["icrash-1"].state == \
            ClaimState.PREPARE_COMPLETED.value
        fresh.unprepare("icrash-1")
        assert fresh.prepared_claims() == {}

    def test_crash_between_ckpt_write_and_fsync(self, tmp_root):
        """InjectedCrash between the checkpoint tmp-file write and its
        fdatasync, during the PrepareCompleted commit of a dynamic
        sub-slice claim: the carve-out exists but its completion never
        became durable. The startup sweep must destroy the orphan
        carve-out and the claim must prepare cleanly afterwards."""
        from k8s_dra_driver_gpu_tpu.pkg import faults
        from k8s_dra_driver_gpu_tpu.pkg.faults import InjectedCrash

        state = DeviceState(Config.mock(root=tmp_root, topology="v5e-4"))
        device = next(n for n in sorted(state.allocatable) if "ss-" in n)
        # after=1: the PrepareStarted commit (write #1) goes through;
        # the PrepareCompleted commit (write #2) crashes pre-fsync.
        with faults.inject("ckpt.fsync", mode="crash", after=1, count=1):
            with pytest.raises((InjectedCrash, RuntimeError)):
                state.prepare(make_claim("icrash-2", [device]))
        # The durable file still checksum-verifies and holds at most
        # the reservation (never the completion).
        fresh_cm = CheckpointManager(tmp_root)
        cp = fresh_cm.get()
        if "icrash-2" in cp.claims:
            assert cp.claims["icrash-2"].state == \
                ClaimState.PREPARE_STARTED.value

        # "Restart": the sweep reconciles the orphan carve-out (its
        # uuid is referenced by no durable completed record)...
        fresh = DeviceState(Config.mock(root=tmp_root, topology="v5e-4"))
        assert fresh._registry.list() == {}
        # ...and the claim lifecycle is healthy again end to end.
        ids = fresh.prepare(make_claim("icrash-2", [device]))
        assert len(ids) == 1
        fresh.unprepare("icrash-2")
        assert fresh.prepared_claims() == {}
        assert fresh._registry.list() == {}

    def test_crash_mode_not_swallowed_by_wire_boundary(self, tmp_root):
        """The Driver's gRPC boundary catches Exception to keep
        serving; a simulated process death must NOT be absorbed into a
        per-claim error string."""
        from k8s_dra_driver_gpu_tpu.kubeletplugin.driver import Driver
        from k8s_dra_driver_gpu_tpu.pkg import faults
        from k8s_dra_driver_gpu_tpu.pkg.faults import InjectedCrash
        from k8s_dra_driver_gpu_tpu.pkg.kubeclient import FakeKubeClient
        from tests.fake_kube import make_claim_dict

        kube = FakeKubeClient()
        obj = make_claim_dict("icrash-3", ["chip-0"])
        kube.create("resource.k8s.io", "v1", "resourceclaims", obj,
                    namespace="default")
        driver = Driver(Config.mock(root=tmp_root, topology="v5e-4"),
                        kube, "n1", enable_health_monitor=False)
        with faults.inject("segment:prep_reserved", mode="crash"):
            with pytest.raises(InjectedCrash):
                driver.prepare_resource_claims(
                    [{"uid": "icrash-3", "namespace": "default",
                      "name": "icrash-3"}])


class TestInFlightGuards:
    def test_unprepare_of_inflight_prepare_rejected(
        self, state, monkeypatch
    ):
        monkeypatch.setenv("TPU_DRA_STALL_AT_SEGMENT", "prep_devices")
        monkeypatch.setenv("TPU_DRA_STALL_SECONDS", "0.5")
        t = threading.Thread(
            target=lambda: state.prepare(make_claim("inf-1", ["chip-0"])))
        t.start()
        try:
            deadline = time.monotonic() + 10
            seen = None
            while time.monotonic() < deadline:
                cp = state.prepared_claims()
                if "inf-1" in cp:
                    seen = cp["inf-1"]
                    break
                time.sleep(0.01)
            assert seen is not None
            from k8s_dra_driver_gpu_tpu.kubeletplugin.device_state import (
                PrepareError,
            )
            with pytest.raises(PrepareError, match="in flight"):
                state.unprepare("inf-1")
        finally:
            t.join()
        # After the prepare lands, unprepare proceeds normally.
        state.unprepare("inf-1")
        assert "inf-1" not in state.prepared_claims()
