"""Pipeline-parallel trainer correctness: a pp_train step over the
virtual 8-device mesh must equal the plain single-device step on the
concatenated microbatch stream (GPipe is exact data parallelism over
microbatches -- same loss, same updated params), across pp x dp layouts.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from k8s_dra_driver_gpu_tpu.models import llama
from k8s_dra_driver_gpu_tpu.parallel.mesh import build_pipeline_mesh
from k8s_dra_driver_gpu_tpu.train.pp_train import make_pp_train
from k8s_dra_driver_gpu_tpu.train.train import loss_fn


def f32_cfg(n_layers=4):
    """Tiny config in float32 so the equivalence checks are tight (the
    schedule reorders no math, only where it runs; fp32 keeps the
    comparison free of bf16 rounding noise)."""
    return dataclasses.replace(
        llama.LlamaConfig.tiny(), n_layers=n_layers, dtype=jnp.float32,
        remat="none")


def make_tokens(key, M, B, S, vocab):
    return jax.random.randint(key, (M, B, S + 1), 0, vocab, jnp.int32)


def reference_loss(params, tokens, cfg):
    """Mean loss over the flattened [M*B, S+1] batch on one device."""
    flat = tokens.reshape(-1, tokens.shape[-1])
    return loss_fn(params, flat, cfg)


def sgd(lr=0.1):
    return optax.sgd(lr)


class TestPpTrain:
    @pytest.mark.parametrize("pp,dp,M", [(4, 2, 4), (8, 1, 3), (2, 4, 2)])
    def test_step_matches_single_device(self, pp, dp, M):
        cfg = f32_cfg(n_layers=8)
        mesh = build_pipeline_mesh(pp, dp)
        params = llama.init(jax.random.PRNGKey(0), cfg)
        tokens = make_tokens(jax.random.PRNGKey(1), M, 2 * dp, 16,
                             cfg.vocab_size)

        init_fn, step_fn, batch_shard, place = make_pp_train(
            mesh, cfg, n_microbatches=M, optimizer=sgd())
        state = init_fn(place(params))
        state, loss = step_fn(state, jax.device_put(tokens, batch_shard))

        ref_loss, ref_grads = jax.value_and_grad(reference_loss)(
            params, tokens, cfg)
        ref_params = jax.tree.map(lambda p, g: p - 0.1 * g,
                                  params, ref_grads)

        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   rtol=1e-5, atol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
            jax.device_get(state.params), ref_params)

    def test_loss_decreases(self):
        cfg = f32_cfg(n_layers=4)
        mesh = build_pipeline_mesh(4, 2)
        init_fn, step_fn, batch_shard, place = make_pp_train(
            mesh, cfg, n_microbatches=2)
        state = init_fn(place(llama.init(jax.random.PRNGKey(0), cfg)))
        tokens = jax.device_put(
            make_tokens(jax.random.PRNGKey(1), 2, 4, 16, cfg.vocab_size),
            batch_shard)
        first = None
        for _ in range(5):
            state, loss = step_fn(state, tokens)
            first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_layers_actually_sharded_over_pp(self):
        cfg = f32_cfg(n_layers=8)
        mesh = build_pipeline_mesh(4, 2)
        init_fn, step_fn, batch_shard, place = make_pp_train(
            mesh, cfg, n_microbatches=2)
        params = place(llama.init(jax.random.PRNGKey(0), cfg))
        wq = params["layers"]["wq"]
        # 8 stacked layers over pp=4: each device holds a 2-layer block.
        shard_shapes = {s.data.shape for s in wq.addressable_shards}
        assert shard_shapes == {(2,) + wq.shape[1:]}
        # Replicated leaves stay whole everywhere.
        embed = params["embed"]
        assert {s.data.shape for s in embed.addressable_shards} == {
            embed.shape}

    def test_rejects_microbatch_count_mismatch(self):
        cfg = f32_cfg(n_layers=4)
        mesh = build_pipeline_mesh(4, 2)
        init_fn, step_fn, batch_shard, place = make_pp_train(
            mesh, cfg, n_microbatches=4)
        state = init_fn(place(llama.init(jax.random.PRNGKey(0), cfg)))
        bad = jax.device_put(
            make_tokens(jax.random.PRNGKey(1), 2, 4, 16, cfg.vocab_size),
            batch_shard)
        with pytest.raises(ValueError, match=r"must be \[M=4"):
            step_fn(state, bad)

    def test_rejects_indivisible_layers(self):
        cfg = f32_cfg(n_layers=6)
        mesh = build_pipeline_mesh(4, 2)
        with pytest.raises(ValueError, match="not divisible"):
            make_pp_train(mesh, cfg, n_microbatches=2)

    def test_remat_policy_matches_no_remat(self):
        """cfg.remat changes memory, never the math."""
        mesh = build_pipeline_mesh(2, 4)
        losses = {}
        for remat in ("none", "full"):
            cfg = dataclasses.replace(f32_cfg(n_layers=4), remat=remat)
            init_fn, step_fn, batch_shard, place = make_pp_train(
                mesh, cfg, n_microbatches=2, optimizer=sgd())
            state = init_fn(place(llama.init(jax.random.PRNGKey(0), cfg)))
            tokens = jax.device_put(
                make_tokens(jax.random.PRNGKey(1), 2, 4, 16, cfg.vocab_size),
                batch_shard)
            _, loss = step_fn(state, tokens)
            losses[remat] = float(loss)
        np.testing.assert_allclose(losses["none"], losses["full"], rtol=1e-6)
