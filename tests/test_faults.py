"""Fault-injection harness tests (pkg/faults).

The registry itself (modes, probability determinism, count/after caps,
env grammar) plus the compiled-in seams: SegmentTimer segments, flock
acquisition, checkpoint write/fsync, tpulib enumerate/health, and the
rendezvous handler.
"""

import pytest

from k8s_dra_driver_gpu_tpu.pkg import faults
from k8s_dra_driver_gpu_tpu.pkg.faults import (
    FaultRegistry,
    FaultSpec,
    InjectedCrash,
    InjectedFault,
)


@pytest.fixture(autouse=True)
def clean_registry():
    faults.reset()
    yield
    faults.reset()


class TestRegistry:
    def test_unarmed_point_is_noop(self):
        faults.fault_point("nothing.armed")  # must not raise

    def test_error_mode_default_exception(self):
        faults.arm("p1", mode="error")
        with pytest.raises(InjectedFault):
            faults.fault_point("p1")

    def test_error_mode_call_site_factory(self):
        faults.arm("p1", mode="error")
        with pytest.raises(OSError, match="injected"):
            faults.fault_point("p1", error=lambda m: OSError(m))

    def test_crash_mode_is_base_exception(self):
        """InjectedCrash must sail through `except Exception` wire
        boundaries -- that's the whole point of the crash mode."""
        faults.arm("p1", mode="crash")
        with pytest.raises(InjectedCrash):
            try:
                faults.fault_point("p1")
            except Exception:  # noqa: BLE001
                pytest.fail("InjectedCrash was swallowed by except Exception")

    def test_count_caps_fires(self):
        faults.arm("p1", mode="error", count=2)
        for _ in range(2):
            with pytest.raises(InjectedFault):
                faults.fault_point("p1")
        faults.fault_point("p1")  # third evaluation: capped, no raise
        assert faults.snapshot()["fires"]["p1"] == 2
        assert faults.snapshot()["evaluations"]["p1"] == 3

    def test_after_skips_first_evaluations(self):
        faults.arm("p1", mode="error", after=2)
        faults.fault_point("p1")
        faults.fault_point("p1")
        with pytest.raises(InjectedFault):
            faults.fault_point("p1")

    def test_latency_mode_sleeps_and_continues(self):
        import time

        faults.arm("p1", mode="latency", latency=0.05)
        t0 = time.monotonic()
        faults.fault_point("p1")
        assert time.monotonic() - t0 >= 0.04

    def test_probability_deterministic_under_seed(self):
        def schedule(seed):
            reg = FaultRegistry(seed=seed)
            reg.arm(FaultSpec(point="p", probability=0.5))
            fired = []
            for _ in range(32):
                try:
                    reg.fire("p")
                    fired.append(0)
                except InjectedFault:
                    fired.append(1)
            return fired

        a, b, c = schedule(7), schedule(7), schedule(8)
        assert a == b
        assert a != c  # different seed, different schedule
        assert 0 < sum(a) < 32  # actually probabilistic

    def test_inject_context_manager_disarms(self):
        with faults.inject("p1", mode="error"):
            with pytest.raises(InjectedFault):
                faults.fault_point("p1")
        faults.fault_point("p1")

    def test_env_grammar(self):
        reg = FaultRegistry()
        n = reg.configure_from_env({
            "TPU_DRA_FAULTS":
                "kube.request:error:p=0.3:count=5;ckpt.fsync:crash:count=1;"
                "flock.acquire:latency:latency=0.01",
            "TPU_DRA_FAULTS_SEED": "42",
        })
        assert n == 3
        assert set(reg.snapshot()["armed"]) == {
            "kube.request", "ckpt.fsync", "flock.acquire"}

    def test_env_bad_specs_ignored(self):
        reg = FaultRegistry()
        assert reg.configure_from_env(
            {"TPU_DRA_FAULTS": "p:badmode;q:error:bogus=1;ok:error"}) == 1

    def test_unknown_mode_rejected(self):
        with pytest.raises(ValueError):
            FaultSpec(point="p", mode="teleport")


class TestSeams:
    def test_segment_seam(self):
        from k8s_dra_driver_gpu_tpu.pkg.timing import SegmentTimer

        timer = SegmentTimer("op")
        with faults.inject("segment:prep_devices", mode="error"):
            with pytest.raises(InjectedFault):
                with timer.segment("prep_devices"):
                    pass
            with timer.segment("other_segment"):
                pass  # other segments unaffected

    def test_flock_seam(self, tmp_path):
        from k8s_dra_driver_gpu_tpu.pkg.flock import Flock, FlockTimeoutError

        lock = Flock(str(tmp_path / "l.lock"))
        with faults.inject("flock.acquire", mode="error"):
            with pytest.raises(FlockTimeoutError):
                lock.acquire(timeout=0.5)
        with lock.acquire(timeout=0.5):
            pass  # seam disarmed: lock healthy (and was never leaked)

    def test_ckpt_fsync_seam_fails_commit_cleanly(self, tmp_path):
        from k8s_dra_driver_gpu_tpu.kubeletplugin.checkpoint import (
            CheckpointedClaim,
            CheckpointManager,
            ClaimState,
        )

        cm = CheckpointManager(str(tmp_path), boot_id="b1")
        cm.update_claim("keep", CheckpointedClaim(
            uid="keep", state=ClaimState.PREPARE_STARTED.value))
        with faults.inject("ckpt.fsync", mode="error"):
            with pytest.raises(RuntimeError):
                cm.update_claim("lost", CheckpointedClaim(
                    uid="lost", state=ClaimState.PREPARE_STARTED.value))
        # Poisoned cache re-reads the durable file: only "keep" survives.
        assert set(cm.get().claims) == {"keep"}

    def test_tpulib_seams(self):
        from k8s_dra_driver_gpu_tpu.tpulib.binding import (
            EnumerateOptions,
            PyTpuLib,
            TpuLibError,
        )

        lib = PyTpuLib()
        opts = EnumerateOptions(mock_topology="v5e-4")
        with faults.inject("tpulib.enumerate", mode="error"):
            with pytest.raises(TpuLibError):
                lib.enumerate(opts)
        with faults.inject("tpulib.health", mode="error"):
            with pytest.raises(TpuLibError):
                lib.health(opts)
        assert len(lib.enumerate(opts).chips) == 4  # disarmed: clean

    def test_kube_request_seam_via_retrying_client(self):
        from k8s_dra_driver_gpu_tpu.pkg.kubeclient import (
            FakeKubeClient,
            KubeError,
        )
        from k8s_dra_driver_gpu_tpu.pkg.retry import (
            RetryingKubeClient,
            RetryPolicy,
        )

        rk = RetryingKubeClient(
            FakeKubeClient(),
            policy=RetryPolicy(base_delay=0.001, max_delay=0.002,
                               deadline_s=0.01))
        with faults.inject("kube.request", mode="error"):
            with pytest.raises(KubeError) as e:
                rk.server_version()
            assert e.value.status == 503
        assert rk.retry_count > 0
