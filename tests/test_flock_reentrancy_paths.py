"""FlockReentrantError regression coverage for the CD-plugin and
daemon paths.

PR 1 made re-entrant Flock acquisition fail fast (FlockReentrantError
instead of a silent 10s timeout burn) but only covered the GPU-plugin
path (tests/test_pkg_infra.py + kubeletplugin flows). The compute-
domain plugin owns its own checkpoint flock
(computedomain/plugin/device_state.py), and the daemon's supervisor
(computedomain/daemon/process.py) carries the same non-reentrant-lock
discipline with a threading.Lock -- both get pinned here so a future
refactor that introduces a nested acquire dies in CI within seconds,
not as a field stall.
"""

import signal
import sys
import threading
import time

import pytest

from k8s_dra_driver_gpu_tpu.computedomain.daemon.process import (
    ProcessManager,
)
from k8s_dra_driver_gpu_tpu.computedomain.plugin.device_state import (
    CDDeviceState,
)
from k8s_dra_driver_gpu_tpu.kubeletplugin.checkpoint import (
    CheckpointedClaim,
    ClaimState,
)
from k8s_dra_driver_gpu_tpu.pkg.flock import FlockReentrantError
from k8s_dra_driver_gpu_tpu.pkg.kubeclient import FakeKubeClient

# Re-entrancy must fail FAST: well under the 10s flock timeout it
# used to burn as fake cross-process contention.
FAST_S = 2.0


@pytest.fixture()
def cd_state(tmp_root):
    state = CDDeviceState(tmp_root, FakeKubeClient(), "node-0",
                          use_informer=False)
    yield state
    state.stop()


class TestCDPluginCheckpointReentrancy:
    def test_commit_fn_reentering_checkpoint_fails_fast(self, cd_state):
        """A commit mutation that calls back into its own
        CheckpointManager (get/update while the flush holds the
        checkpoint flock) is the CD-plugin shape of the re-entrancy
        bug. It must surface FlockReentrantError immediately."""
        cm = cd_state._checkpoint

        def reentrant(cp):
            cm.get()  # same flock, same thread: the bug under test

        t0 = time.monotonic()
        with pytest.raises(RuntimeError) as exc_info:
            cm.update(reentrant)
        elapsed = time.monotonic() - t0
        assert isinstance(exc_info.value.__cause__, FlockReentrantError)
        assert elapsed < FAST_S, (
            f"re-entrant acquire burned {elapsed:.1f}s as fake contention"
        )

    def test_nested_update_from_commit_fn_fails_fast(self, cd_state):
        """Re-entering the group-commit machinery itself (not just the
        flock) used to park the flusher on its own queue FOREVER -- an
        unbounded stall, worse than the 10s the flock case burned.
        Now it fails fast with the same FlockReentrantError contract."""
        cm = cd_state._checkpoint

        def nested(cp):
            cm.update_claim("inner", None)

        t0 = time.monotonic()
        with pytest.raises(RuntimeError) as exc_info:
            cm.update(nested)
        assert time.monotonic() - t0 < FAST_S
        assert isinstance(exc_info.value.__cause__, FlockReentrantError)
        assert "re-entered" in str(exc_info.value.__cause__)

    def test_state_survives_the_failed_reentrant_commit(self, cd_state):
        """After the fast failure the checkpoint is intact and the CD
        plugin's normal single-phase lifecycle still works."""
        cm = cd_state._checkpoint
        with pytest.raises(RuntimeError):
            cm.update(lambda cp: cm.get())

        def complete(cp):
            cp.claims["cd-claim"] = CheckpointedClaim(
                uid="cd-claim",
                state=ClaimState.PREPARE_COMPLETED.value)

        cm.update(complete)
        assert set(cd_state.prepared_claims()) == {"cd-claim"}
        cd_state.unprepare("cd-claim")
        assert cd_state.prepared_claims() == {}


class _SleepChild:
    """A ProcessManager running a long-sleeping python child."""

    ARGV = [sys.executable, "-c", "import time; time.sleep(60)"]

    def __init__(self, pidfile=None):
        self.pm = ProcessManager(list(self.ARGV), pidfile=pidfile)


class TestDaemonProcessManagerLockDiscipline:
    """process.py uses a non-reentrant threading.Lock with the same
    rule the flocks follow: never call back into a lock-taking method
    while holding it, never sleep under it. These pin the observable
    contract (methods stay responsive around the watchdog's backoff
    sleep) so a refactor that moves the sleep under the lock -- the
    threading.Lock twin of the FlockReentrantError bug -- fails here
    fast instead of deadlocking a daemon in the field."""

    def test_api_responsive_while_watchdog_handles_crash(self):
        child = _SleepChild()
        pm = child.pm
        pm.ensure_started()
        pm.start_watchdog()
        try:
            # Kill the child: the watchdog notices and sleeps its 1s
            # backoff OUTSIDE the lock before restarting.
            pm.signal(signal.SIGKILL)
            deadline = time.monotonic() + 5
            while pm.alive() and time.monotonic() < deadline:
                time.sleep(0.02)
            # While the watchdog is in its backoff window, every
            # lock-taking API must answer promptly from other threads.
            results = {}

            def probe():
                t0 = time.monotonic()
                results["alive"] = pm.alive()
                results["pid"] = pm.pid
                results["elapsed"] = time.monotonic() - t0

            t = threading.Thread(target=probe)
            t.start()
            t.join(timeout=FAST_S)
            assert not t.is_alive(), (
                "alive()/pid blocked: a lock is held across the "
                "watchdog backoff sleep"
            )
            assert results["elapsed"] < FAST_S
        finally:
            pm.stop()
        assert not pm.alive()

    def test_stop_during_backoff_does_not_deadlock(self):
        child = _SleepChild()
        pm = child.pm
        pm.ensure_started()
        pm.start_watchdog()
        pm.signal(signal.SIGKILL)
        time.sleep(0.1)  # let the watchdog observe the death
        t0 = time.monotonic()
        pm.stop()  # takes the lock + joins the watchdog
        elapsed = time.monotonic() - t0
        assert elapsed < 8.0, f"stop() took {elapsed:.1f}s"
        assert not pm.alive()

    def test_restart_is_not_reentrant_from_signal_path(self):
        """restart() and ensure_started() both take the lock; calling
        one from under the other would self-deadlock (the
        threading.Lock analog of FlockReentrantError). Pin that the
        public methods run lock-balanced: a tight interleaved sequence
        from two threads completes promptly."""
        child = _SleepChild()
        pm = child.pm
        pm.ensure_started()
        errors = []

        def churn():
            try:
                for _ in range(3):
                    pm.restart()
                    pm.ensure_started()
                    pm.alive()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=churn) for _ in range(2)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        alive = [t for t in threads if t.is_alive()]
        try:
            assert not alive, "restart/ensure_started churn deadlocked"
            assert not errors, errors
            assert time.monotonic() - t0 < 30
        finally:
            pm.stop()
