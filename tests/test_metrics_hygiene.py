"""Metrics hygiene: every component registry composes, scrapes as
valid Prometheus exposition, carries no duplicate family names -- and
every histogram declared in pkg/metrics.py has a real producer call
site, so a dead metric (declared, dashboarded, never observed) fails
at PR time instead of shipping.
"""

import ast
import os
import re

import pytest
from prometheus_client import CollectorRegistry, generate_latest
from prometheus_client.parser import text_string_to_metric_families

from k8s_dra_driver_gpu_tpu.pkg.metrics import (
    AutoscaleMetrics,
    ClaimSLOMetrics,
    ComputeDomainMetrics,
    DefragMetrics,
    DRARequestMetrics,
    FleetMetrics,
    PartitionMetrics,
    PlacementMetrics,
    RecoveryMetrics,
    ResilienceMetrics,
    SchedulerMetrics,
    register_build_info,
)

PKG_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "k8s_dra_driver_gpu_tpu")
METRICS_PY = os.path.join(PKG_DIR, "pkg", "metrics.py")


def _compose(builders) -> CollectorRegistry:
    """Build one registry the way the binaries do: the first class
    owns it, the rest join it."""
    first = builders[0]()
    for cls in builders[1:]:
        cls(registry=first.registry)
    return first.registry


# The three real binaries' registry compositions (kubeletplugin/main,
# pkg/scheduler main, computedomain mains). A pairing that declares
# the same family twice raises at construction -- this test IS the
# compile check for registry composition.
COMPOSITIONS = {
    "kubelet-plugin": (DRARequestMetrics, ResilienceMetrics,
                       RecoveryMetrics, PartitionMetrics),
    "scheduler": (PlacementMetrics, SchedulerMetrics, FleetMetrics,
                  ResilienceMetrics, RecoveryMetrics, DefragMetrics,
                  AutoscaleMetrics),
    "cd-plugin": (DRARequestMetrics, ResilienceMetrics,
                  RecoveryMetrics),
    "cd-controller": (ComputeDomainMetrics, ResilienceMetrics),
}


@pytest.mark.parametrize("name", sorted(COMPOSITIONS))
def test_registry_scrapes_clean(name):
    registry = _compose(COMPOSITIONS[name])
    # Every binary's main also stamps the build-info gauge; it must
    # compose (and scrape) cleanly alongside every metric class.
    register_build_info(registry)
    text = generate_latest(registry).decode()
    families = list(text_string_to_metric_families(text))
    assert families, f"{name}: empty scrape"
    seen = [f.name for f in families]
    dupes = {n for n in seen if seen.count(n) > 1}
    assert not dupes, f"{name}: duplicate metric families {dupes}"


@pytest.mark.parametrize("name", sorted(COMPOSITIONS))
def test_build_info_gauge(name):
    """Every binary's registry exposes tpu_dra_build_info with the
    VERSION-file version and the active feature-gate set (the
    rollout-pivot labels)."""
    from k8s_dra_driver_gpu_tpu import __version__
    from k8s_dra_driver_gpu_tpu.pkg.featuregates import FeatureGates

    registry = _compose(COMPOSITIONS[name])
    register_build_info(registry, FeatureGates.parse(
        "DynamicSubSlice=true"))
    text = generate_latest(registry).decode()
    [fam] = [f for f in text_string_to_metric_families(text)
             if f.name == "tpu_dra_build_info"]
    [sample] = fam.samples
    assert sample.value == 1
    assert sample.labels["version"] == __version__
    # VERSION file is the single source of truth the gauge re-exports.
    with open(os.path.join(os.path.dirname(PKG_DIR), "VERSION"),
              encoding="utf-8") as f:
        assert sample.labels["version"] == f.read().strip().lstrip("v")
    gates = sample.labels["feature_gates"].split(",")
    assert "DynamicSubSlice" in gates
    assert "ChipHealthCheck" in gates  # default-on gate is "active"


# Dimensionless-by-design exceptions to the unit-suffix rule: ratios
# and pure counts whose unit IS the quantity. Add here consciously.
_UNITLESS_OK = {
    "tpu_dra_placement_compactness",  # max ICI hops (a hop count)
    "tpu_dra_chip_duty_cycle",        # 0.0-1.0 ratio
    "tpu_dra_fleet_pool_utilization",  # 0.0-1.0 ratio
    "tpu_dra_placement_frag_score",   # 0.0-1.0 score
}


def test_metric_naming_conventions():
    """Prometheus naming-convention gate over EVERY composed registry:
    lowercase names only, counters end `_total`, nothing else does,
    and time/size metrics carry their `_seconds`/`_bytes` unit suffix
    -- so new telemetry metrics can't drift from the convention the
    dashboards (deployments/grafana) key on."""
    lower = re.compile(r"^[a-z][a-z0-9_]*$")
    for comp_name, builders in COMPOSITIONS.items():
        registry = _compose(builders)
        register_build_info(registry)
        for fam in registry.collect():
            for sample in fam.samples:
                n = sample.name
                assert lower.match(n), (
                    f"{comp_name}: metric name {n!r} violates "
                    "lowercase_with_underscores")
            base = fam.name
            if fam.type == "counter":
                for sample in fam.samples:
                    if sample.name.endswith("_created"):
                        continue  # prometheus_client bookkeeping
                    assert sample.name.endswith("_total"), (
                        f"{comp_name}: counter sample {sample.name!r} "
                        "must end _total")
            else:
                assert not base.endswith("_total"), (
                    f"{comp_name}: non-counter {base!r} must not "
                    "claim the _total suffix")
            # Unit suffixes: a name that mentions a unit must END with
            # it (tpu_dra_seconds_to_x-style misorderings drift
            # dashboards).
            for unit in ("seconds", "bytes"):
                if unit in base:
                    assert base.endswith(f"_{unit}") or \
                        base.endswith("_total"), (
                            f"{comp_name}: {base!r} mentions "
                            f"{unit!r} but does not end _{unit}")
            if fam.type == "histogram":
                assert base.endswith(("_seconds", "_bytes")) or \
                    base in _UNITLESS_OK, (
                        f"{comp_name}: histogram {base!r} has no unit "
                        "suffix; add one or register it in "
                        "_UNITLESS_OK consciously")


def test_exemplar_observation_scrapes_clean():
    """The SLO histogram's trace-id exemplars must not break the text
    exposition (exemplars render only in openmetrics)."""
    slo = ClaimSLOMetrics()
    slo.observe("fit", 0.01, trace_id="ab" * 16)
    slo.observe("prepare", 0.02)  # exemplar-less path
    text = generate_latest(slo.registry).decode()
    fams = {f.name for f in text_string_to_metric_families(text)}
    assert "tpu_dra_claim_e2e_seconds" in fams
    assert 'phase="fit"' in text


def _declared_histograms() -> dict[str, str]:
    """attr name -> metric name for every ``self.X = Histogram(...)``
    in pkg/metrics.py."""
    tree = ast.parse(open(METRICS_PY, encoding="utf-8").read())
    out = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        fn = node.value.func
        if not (isinstance(fn, ast.Name) and fn.id == "Histogram"):
            continue
        target = node.targets[0]
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            metric_name = node.value.args[0].value
            out[target.attr] = metric_name
    return out


# attr -> regex that must match somewhere in the package tree OUTSIDE
# the declaration itself: the PRODUCER call-site proof. A new
# histogram without an entry here (or whose producer pattern matches
# nothing) fails the test -- add the producer first, then the row.
PRODUCERS = {
    "duration": r"\.observe\(",            # DRARequestMetrics.observe ctx
    "prepare_segment": r"observe_segments",
    "e2e": r"\.slo\.observe\(|self\.slo\.observe\(",
    "compactness": r"\.compactness\.labels\(",
    "wait": r"observe_wait\(",
    "sync_seconds": r"\.sync_seconds\.labels\(",
    "snapshot_build": r"\.snapshot_build\.observe\(",
    "snapshot_delta": r"\.snapshot_delta\.labels\(",
    "relist_backoff": r"\.relist_backoff\.labels\(",
    "fold_seconds": r"fold_hist\.observe\(",
    "move_seconds": r"\.move_seconds\.observe\(",
    "rollout_seconds": r"\.rollout_seconds\.observe\(",
    # MigrationMetrics (pkg/migration.py). move_seconds shares its attr
    # name with DefragMetrics, so the row above already covers it.
    "ack_seconds": r"\.ack_seconds\.observe\(",
    "switch_seconds": r"\.switch_seconds\.observe\(",
}


# Same producer-proof contract for the ISSUE-15 power/pre-warm
# counters and gauge (they are not histograms, so the AST scan above
# does not see them): metric name -> call-site regex that must match
# in the package tree outside pkg/metrics.py.
COUNTER_PRODUCERS = {
    "tpu_dra_fleet_power_headroom_watts": r"set_pool_power",
    "tpu_dra_prewarm_created_total": r"inc_prewarm_created\(",
    "tpu_dra_prewarm_hit_total": r"inc_prewarm_hit\(",
    "tpu_dra_prewarm_reaped_total": r"inc_prewarm_reaped\(",
}


def test_power_prewarm_metrics_have_producers():
    sources = list(_package_sources())
    for metric, pattern in COUNTER_PRODUCERS.items():
        rx = re.compile(pattern)
        hits = [path for path, text in sources
                if rx.search(text)
                and not path.endswith(os.path.join("pkg",
                                                   "metrics.py"))]
        assert hits, (
            f"{metric!r} has no producer call site matching "
            f"{pattern!r} outside pkg/metrics.py -- dead metric")


def _package_sources():
    for root, _dirs, files in os.walk(PKG_DIR):
        if "__pycache__" in root:
            continue
        for fname in files:
            if fname.endswith(".py"):
                path = os.path.join(root, fname)
                yield path, open(path, encoding="utf-8",
                                 errors="replace").read()


def test_every_declared_histogram_has_a_producer():
    declared = _declared_histograms()
    assert declared, "no histograms parsed out of pkg/metrics.py"
    missing_rows = set(declared) - set(PRODUCERS)
    assert not missing_rows, (
        f"histogram(s) {sorted(missing_rows)} declared in "
        "pkg/metrics.py without a PRODUCERS row in this test: wire a "
        "producer call site, then register its pattern here")
    sources = list(_package_sources())
    for attr, pattern in PRODUCERS.items():
        if attr not in declared:
            continue
        rx = re.compile(pattern)
        hits = [path for path, text in sources
                if rx.search(text)
                and not path.endswith(os.path.join("pkg", "metrics.py"))]
        # metrics.py-internal wrappers (observe/observe_wait/
        # observe_segments/slo.observe) count only through their
        # EXTERNAL callers, which the patterns above match.
        assert hits, (
            f"histogram {declared[attr]!r} ({attr}) has no producer "
            f"call site matching {pattern!r} outside pkg/metrics.py "
            "-- dead metric")
