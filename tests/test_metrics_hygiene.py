"""Metrics hygiene: every component registry composes, scrapes as
valid Prometheus exposition, carries no duplicate family names -- and
every histogram declared in pkg/metrics.py has a real producer call
site, so a dead metric (declared, dashboarded, never observed) fails
at PR time instead of shipping.
"""

import ast
import os
import re

import pytest
from prometheus_client import CollectorRegistry, generate_latest
from prometheus_client.parser import text_string_to_metric_families

from k8s_dra_driver_gpu_tpu.pkg.metrics import (
    ClaimSLOMetrics,
    ComputeDomainMetrics,
    DRARequestMetrics,
    PartitionMetrics,
    PlacementMetrics,
    RecoveryMetrics,
    ResilienceMetrics,
    SchedulerMetrics,
)

PKG_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "k8s_dra_driver_gpu_tpu")
METRICS_PY = os.path.join(PKG_DIR, "pkg", "metrics.py")


def _compose(builders) -> CollectorRegistry:
    """Build one registry the way the binaries do: the first class
    owns it, the rest join it."""
    first = builders[0]()
    for cls in builders[1:]:
        cls(registry=first.registry)
    return first.registry


# The three real binaries' registry compositions (kubeletplugin/main,
# pkg/scheduler main, computedomain mains). A pairing that declares
# the same family twice raises at construction -- this test IS the
# compile check for registry composition.
COMPOSITIONS = {
    "kubelet-plugin": (DRARequestMetrics, ResilienceMetrics,
                       RecoveryMetrics, PartitionMetrics),
    "scheduler": (PlacementMetrics, SchedulerMetrics,
                  ResilienceMetrics, RecoveryMetrics),
    "cd-plugin": (DRARequestMetrics, ResilienceMetrics,
                  RecoveryMetrics),
    "cd-controller": (ComputeDomainMetrics, ResilienceMetrics),
}


@pytest.mark.parametrize("name", sorted(COMPOSITIONS))
def test_registry_scrapes_clean(name):
    registry = _compose(COMPOSITIONS[name])
    text = generate_latest(registry).decode()
    families = list(text_string_to_metric_families(text))
    assert families, f"{name}: empty scrape"
    seen = [f.name for f in families]
    dupes = {n for n in seen if seen.count(n) > 1}
    assert not dupes, f"{name}: duplicate metric families {dupes}"


def test_exemplar_observation_scrapes_clean():
    """The SLO histogram's trace-id exemplars must not break the text
    exposition (exemplars render only in openmetrics)."""
    slo = ClaimSLOMetrics()
    slo.observe("fit", 0.01, trace_id="ab" * 16)
    slo.observe("prepare", 0.02)  # exemplar-less path
    text = generate_latest(slo.registry).decode()
    fams = {f.name for f in text_string_to_metric_families(text)}
    assert "tpu_dra_claim_e2e_seconds" in fams
    assert 'phase="fit"' in text


def _declared_histograms() -> dict[str, str]:
    """attr name -> metric name for every ``self.X = Histogram(...)``
    in pkg/metrics.py."""
    tree = ast.parse(open(METRICS_PY, encoding="utf-8").read())
    out = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        fn = node.value.func
        if not (isinstance(fn, ast.Name) and fn.id == "Histogram"):
            continue
        target = node.targets[0]
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            metric_name = node.value.args[0].value
            out[target.attr] = metric_name
    return out


# attr -> regex that must match somewhere in the package tree OUTSIDE
# the declaration itself: the PRODUCER call-site proof. A new
# histogram without an entry here (or whose producer pattern matches
# nothing) fails the test -- add the producer first, then the row.
PRODUCERS = {
    "duration": r"\.observe\(",            # DRARequestMetrics.observe ctx
    "prepare_segment": r"observe_segments",
    "e2e": r"\.slo\.observe\(|self\.slo\.observe\(",
    "compactness": r"\.compactness\.labels\(",
    "wait": r"observe_wait\(",
    "sync_seconds": r"\.sync_seconds\.labels\(",
    "snapshot_build": r"\.snapshot_build\.observe\(",
}


def _package_sources():
    for root, _dirs, files in os.walk(PKG_DIR):
        if "__pycache__" in root:
            continue
        for fname in files:
            if fname.endswith(".py"):
                path = os.path.join(root, fname)
                yield path, open(path, encoding="utf-8",
                                 errors="replace").read()


def test_every_declared_histogram_has_a_producer():
    declared = _declared_histograms()
    assert declared, "no histograms parsed out of pkg/metrics.py"
    missing_rows = set(declared) - set(PRODUCERS)
    assert not missing_rows, (
        f"histogram(s) {sorted(missing_rows)} declared in "
        "pkg/metrics.py without a PRODUCERS row in this test: wire a "
        "producer call site, then register its pattern here")
    sources = list(_package_sources())
    for attr, pattern in PRODUCERS.items():
        if attr not in declared:
            continue
        rx = re.compile(pattern)
        hits = [path for path, text in sources
                if rx.search(text)
                and not path.endswith(os.path.join("pkg", "metrics.py"))]
        # metrics.py-internal wrappers (observe/observe_wait/
        # observe_segments/slo.observe) count only through their
        # EXTERNAL callers, which the patterns above match.
        assert hits, (
            f"histogram {declared[attr]!r} ({attr}) has no producer "
            f"call site matching {pattern!r} outside pkg/metrics.py "
            "-- dead metric")
