"""Training launcher + multislice mesh tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_gpu_tpu.compat import shard_map
from k8s_dra_driver_gpu_tpu.parallel.mesh import (
    MeshPlan,
    build_multislice_mesh,
)
from k8s_dra_driver_gpu_tpu.train.main import run


class TestLauncher:
    def test_tiny_run(self, caplog):
        import logging

        caplog.set_level(logging.INFO)
        assert run(["--model", "tiny", "--steps", "3",
                    "--batch-size", "4", "--seq-len", "16"]) == 0
        assert any("loss" in r.message for r in caplog.records)

    def test_moe_tiny_run(self, caplog):
        import logging

        caplog.set_level(logging.INFO)
        # The (dp, ep) expert-parallel family through the same launcher;
        # on the 8-device CPU mesh this lands dp=2 x ep=4.
        assert run(["--model", "moe-tiny", "--steps", "2",
                    "--batch-size", "4", "--seq-len", "16"]) == 0
        assert any("'ep'" in r.message or "ep" in str(r.message)
                   for r in caplog.records if "mesh" in r.message)

    def test_steps_per_call_matches_per_step_semantics(self, caplog):
        import logging
        import re

        caplog.set_level(logging.INFO)

        def final_loss():
            msgs = [r.getMessage() for r in caplog.records
                    if r.getMessage().startswith("step 7 ")]
            assert msgs, [r.getMessage() for r in caplog.records]
            return float(re.search(r"loss ([0-9.]+)", msgs[-1]).group(1))

        # 7 steps = 2 scanned dispatches of 3 + 1 per-step tail; the
        # synthetic data is keyed by step, so step semantics identical
        # to the unscanned run mean an identical final loss.
        assert run(["--model", "tiny", "--steps", "7", "--batch-size",
                    "4", "--seq-len", "16", "--steps-per-call", "3"]) == 0
        scanned = final_loss()
        caplog.clear()
        assert run(["--model", "tiny", "--steps", "7", "--batch-size",
                    "4", "--seq-len", "16"]) == 0
        assert abs(scanned - final_loss()) < 2e-3

    def test_steps_per_call_rejected_for_moe(self, capsys):
        with pytest.raises(SystemExit):
            run(["--model", "moe-tiny", "--steps", "2",
                 "--steps-per-call", "2"])

    def test_resume_from_checkpoint(self, tmp_path, caplog):
        import logging

        caplog.set_level(logging.INFO)
        ckpt = str(tmp_path / "ckpt")
        run(["--model", "tiny", "--steps", "2", "--batch-size", "4",
             "--seq-len", "16", "--checkpoint-dir", ckpt])
        caplog.clear()
        # Second invocation resumes at step 2 and continues to 4.
        run(["--model", "tiny", "--steps", "4", "--batch-size", "4",
             "--seq-len", "16", "--checkpoint-dir", ckpt])
        assert any("resumed from step 2" in r.message for r in caplog.records)

    def test_no_distributed_without_env(self, monkeypatch):
        # Without the ComputeDomain channel env, no gang init happens.
        from k8s_dra_driver_gpu_tpu.train.main import initialize_distributed

        initialize_distributed(env={})  # no-op, must not raise


class TestMultisliceMesh:
    def test_two_slices_of_four(self):
        mesh = build_multislice_mesh(
            2, plan=MeshPlan(dp=1, fsdp=1, tp=4, sp=1)
        )
        assert mesh.shape["dcn"] == 2
        assert mesh.shape["tp"] == 4
        # DCN-axis psum crosses the slice boundary.

        out = jax.jit(
            shard_map(
                lambda x: jax.lax.psum(x, "dcn"),
                mesh=mesh,
                in_specs=jax.sharding.PartitionSpec("dcn"),
                out_specs=jax.sharding.PartitionSpec(),
            )
        )(jnp.arange(2, dtype=jnp.float32))
        np.testing.assert_allclose(np.asarray(out), [1.0])

    def test_indivisible_rejected(self):
        with pytest.raises(ValueError):
            build_multislice_mesh(3)


class TestLauncherPipeline:
    def test_pp_run(self, caplog):
        import logging

        caplog.set_level(logging.INFO)
        # pp=2 x dp=4 on the 8-device CPU mesh, 2 microbatches/step.
        assert run(["--model", "tiny", "--steps", "3", "--pp", "2",
                    "--microbatches", "2", "--batch-size", "4",
                    "--seq-len", "16"]) == 0
        assert any("'pp'" in r.message for r in caplog.records
                   if "mesh" in r.message)
        assert any("loss" in r.message for r in caplog.records)

    @pytest.mark.parametrize("argv", [
        ["--pp", "2", "--steps-per-call", "4"],
        ["--pp", "2", "--tp", "2"],
        ["--microbatches", "4"],
        ["--pp", "0"],
        ["--pp", "2", "--microbatches", "0"],
        ["--pp", "2", "--batch-size", "6"],
        ["--model", "moe-tiny", "--pp", "2"],
    ])
    def test_flag_validation(self, argv):
        with pytest.raises(SystemExit):
            run(argv)

    def test_pp_multihost_batch_divisibility_uses_global(self,
                                                         monkeypatch):
        # Multi-host pp is supported (tests/test_multiprocess_gang.py
        # runs the real 2-process job); the flag check must account the
        # GLOBAL batch: on 8 devices / pp=2 -> dp=4, per-process batch
        # 3 in a gang of 2 makes a global batch of 6, indivisible by 4.
        monkeypatch.setenv("TPU_NUM_PROCESSES", "2")
        monkeypatch.setenv("TPU_COORDINATOR_ADDRESS", "")
        with pytest.raises(SystemExit):
            run(["--model", "tiny", "--pp", "2", "--steps", "1",
                 "--batch-size", "3", "--seq-len", "16"])
