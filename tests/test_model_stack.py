"""Tests for the JAX workload stack: llama, mesh, train step, collectives.

Runs on the virtual 8-device CPU mesh (conftest.py), mirroring how the
reference tests multi-node flows without hardware (SURVEY.md §4.4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_gpu_tpu.models import llama
from k8s_dra_driver_gpu_tpu.ops.attention import dot_product_attention
from k8s_dra_driver_gpu_tpu.ops.collectives import bench_allreduce
from k8s_dra_driver_gpu_tpu.parallel.mesh import (
    MeshPlan,
    build_mesh,
    mesh_from_topology,
    plan_for,
)
from k8s_dra_driver_gpu_tpu.train.train import make_sharded_train


class TestMesh:
    def test_plan_factorization(self):
        p = plan_for(8)
        assert p.size == 8
        assert p.tp == 4  # tp takes the innermost power of two up to 4
        p = plan_for(32)
        assert p.size == 32

    def test_build_mesh(self):
        mesh = build_mesh(MeshPlan(dp=2, fsdp=1, tp=4, sp=1))
        assert mesh.shape["dp"] == 2
        assert mesh.shape["tp"] == 4

    def test_mesh_from_topology(self):
        mesh = mesh_from_topology("2x2x2")
        assert int(np.prod(list(mesh.shape.values()))) == 8

    def test_plan_explicit_tp(self):
        p = plan_for(8, tp=2, sp=2)
        assert p.tp == 2 and p.sp == 2 and p.size == 8

    def test_plan_indivisible(self):
        with pytest.raises(ValueError):
            plan_for(8, tp=3)


class TestAttention:
    def test_causal_masking(self):
        # Future tokens must not influence earlier outputs.
        key = jax.random.PRNGKey(0)
        q, k, v = (
            jax.random.normal(key, (1, 8, 4, 16), jnp.float32) for _ in range(3)
        )
        out1 = dot_product_attention(q, k, v, causal=True)
        k2 = k.at[:, -1].set(99.0)
        v2 = v.at[:, -1].set(99.0)
        out2 = dot_product_attention(q, k2, v2, causal=True)
        np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], atol=1e-5)
        assert not np.allclose(out1[:, -1], out2[:, -1])

    def test_gqa_matches_mha_when_equal_heads(self):
        key = jax.random.PRNGKey(1)
        q = jax.random.normal(key, (2, 6, 4, 8))
        k = jax.random.normal(key, (2, 6, 4, 8))
        v = jax.random.normal(key, (2, 6, 4, 8))
        out = dot_product_attention(q, k, v, causal=False)
        # Reference einsum per-head computation.
        scores = jnp.einsum("bqhd,bshd->bhqs", q, k) / np.sqrt(8)
        w = jax.nn.softmax(scores, axis=-1)
        ref = jnp.einsum("bhqs,bshd->bqhd", w, v)
        np.testing.assert_allclose(out, ref, atol=1e-5)


class TestLlama:
    def test_forward_shapes(self):
        cfg = llama.LlamaConfig.tiny()
        params = llama.init(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = jax.jit(lambda p, t: llama.forward(p, t, cfg))(params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32
        assert np.isfinite(np.asarray(logits)).all()

    def test_causality_end_to_end(self):
        cfg = llama.LlamaConfig.tiny()
        params = llama.init(jax.random.PRNGKey(0), cfg)
        t1 = jnp.zeros((1, 8), jnp.int32)
        t2 = t1.at[0, -1].set(5)
        l1 = llama.forward(params, t1, cfg)
        l2 = llama.forward(params, t2, cfg)
        np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=2e-2)


class TestShardedTraining:
    def test_one_step_8dev(self):
        from k8s_dra_driver_gpu_tpu.parallel.mesh import plan_for

        mesh = build_mesh(plan_for(8))
        cfg = llama.LlamaConfig.tiny()
        init_fn, step_fn, batch_shard, place = make_sharded_train(mesh, cfg)
        state = init_fn(place(llama.init(jax.random.PRNGKey(0), cfg)))
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0,
                               cfg.vocab_size, jnp.int32),
            batch_shard,
        )
        state, loss0 = step_fn(state, tokens)
        for _ in range(5):
            state, loss = step_fn(state, tokens)
        # Loss decreases on a repeated batch (the step actually trains).
        assert float(loss) < float(loss0)
        assert int(state.step) == 6
        # Params are really sharded: a tp-sharded leaf spans devices.
        wq = state.params["layers"]["wq"]
        assert len(wq.sharding.device_set) > 1

    def test_graft_entry(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == 2

    def test_graft_dryrun(self, capsys):
        import __graft_entry__ as g

        g.dryrun_multichip(8)
        assert "loss=" in capsys.readouterr().out


class TestCollectives:
    def test_allreduce_bench_runs(self):
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=8, sp=1))
        res = bench_allreduce(mesh, "tp", nbytes=1 << 20, iters=2)
        assert res["participants"] == 8
        assert res["gbps"] > 0


class TestPjitAttentionPin:
    """The pjit-based trainers/serving pin attn_impl auto -> einsum: a
    pallas_call inside jit with sharded operands does not partition
    (XLA gathers the full arrays), so auto must never reach the kernel
    there. Simulated-TPU backend + a booby-trapped kernel prove the
    einsum path is taken; the booby trap itself is validated by calling
    the dispatcher directly."""

    @pytest.fixture()
    def tpu_backend_with_trapped_flash(self, monkeypatch):
        import k8s_dra_driver_gpu_tpu.ops as ops_pkg
        import k8s_dra_driver_gpu_tpu.ops.flash_attention as fa

        monkeypatch.setattr(ops_pkg, "is_tpu_backend", lambda: True)

        def trap(*a, **k):
            raise AssertionError("flash kernel reached under pjit")

        monkeypatch.setattr(fa, "flash_attention", trap)

    def test_trap_fires_through_auto_dispatch(
            self, tpu_backend_with_trapped_flash):
        from k8s_dra_driver_gpu_tpu.ops.attention import attention

        q = jnp.zeros((1, 2048, 2, 128), jnp.bfloat16)
        with pytest.raises(AssertionError, match="flash kernel reached"):
            attention(q, q, q, impl="auto")

    def test_sharded_train_auto_takes_einsum(
            self, tpu_backend_with_trapped_flash):
        # Flash-eligible shape (S=2048, hd=128) through the pjit
        # trainer: the auto->einsum pin must keep the trap unsprung.
        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2))
        cfg = llama.LlamaConfig(
            vocab_size=128, d_model=256, n_layers=1, n_heads=2,
            n_kv_heads=2, d_ff=384)
        assert cfg.head_dim == 128 and cfg.attn_impl == "auto"
        init_fn, step_fn, batch_shard, place = make_sharded_train(
            mesh, cfg)
        state = init_fn(place(llama.init(jax.random.PRNGKey(0), cfg)))
        toks = jnp.zeros((8, 2049), jnp.int32)
        state, loss = step_fn(state, jax.device_put(toks, batch_shard))
        assert jnp.isfinite(loss)

    def test_single_device_mesh_keeps_auto(
            self, tpu_backend_with_trapped_flash):
        # No sharding to destroy on one device: the pin must NOT fire
        # (the kernel is the single-chip long-context enabler), so the
        # trap IS reached through the trainer's auto dispatch.
        mesh = build_mesh(MeshPlan(dp=1, fsdp=1, tp=1),
                          devices=jax.devices()[:1])
        cfg = llama.LlamaConfig(
            vocab_size=128, d_model=256, n_layers=1, n_heads=2,
            n_kv_heads=2, d_ff=384)
        init_fn, step_fn, batch_shard, place = make_sharded_train(
            mesh, cfg)
        state = init_fn(place(llama.init(jax.random.PRNGKey(0), cfg)))
        toks = jnp.zeros((2, 2049), jnp.int32)
        with pytest.raises(Exception, match="flash kernel reached"):
            step_fn(state, jax.device_put(toks, batch_shard))

    def test_sharded_generate_auto_takes_einsum(
            self, tpu_backend_with_trapped_flash):
        from k8s_dra_driver_gpu_tpu.models.decode import (
            make_sharded_generate,
        )

        mesh = build_mesh(MeshPlan(dp=2, fsdp=2, tp=2))
        cfg = llama.LlamaConfig(
            vocab_size=128, d_model=256, n_layers=1, n_heads=2,
            n_kv_heads=2, d_ff=384)
        gen_fn, prompt_shard, place = make_sharded_generate(
            mesh, cfg, max_new_tokens=2, max_len=2048)
        prompt = jnp.zeros((8, 1024), jnp.int32)
        out = gen_fn(place(llama.init(jax.random.PRNGKey(0), cfg)),
                     jax.device_put(prompt, prompt_shard))
        assert out.shape == (8, 2)
