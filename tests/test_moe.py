"""Expert-parallel MoE tests: sharded mixture must equal the
single-device computation."""

import jax
import jax.numpy as jnp
import numpy as np

from k8s_dra_driver_gpu_tpu.models.moe import (
    init_moe,
    make_sharded_moe,
    moe_ffn,
)
from k8s_dra_driver_gpu_tpu.parallel.mesh import Mesh


def ep_mesh(n=8):
    return Mesh(np.asarray(jax.devices()[:n]), ("ep",))


class TestMoE:
    def test_single_device_shapes_and_mixture(self):
        params = init_moe(jax.random.PRNGKey(0), d_model=16, d_ff=32,
                          n_experts=8)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 16))
        out, aux = moe_ffn(params, x, top_k=2, dtype=jnp.float32)
        assert out.shape == x.shape
        assert float(aux) > 0
        # Strict mixture: an expert DETERMINISTICALLY excluded from every
        # top-2 must have zero influence. With all-positive inputs, a
        # large-negative router column gives that expert the smallest
        # logit for every token (logit = -1e3 * sum(x), sum(x) > 0).
        x = jnp.abs(x) + 0.1
        banned = 5
        rigged = dict(params)
        rigged["router"] = params["router"].at[:, banned].set(-1e3)
        out1, _ = moe_ffn(rigged, x, top_k=2, dtype=jnp.float32)
        perturbed = dict(rigged)
        perturbed["w_out"] = rigged["w_out"].at[banned].add(100.0)
        out2, _ = moe_ffn(perturbed, x, top_k=2, dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))

    def test_expert_parallel_matches_single_device(self):
        mesh = ep_mesh(8)
        params = init_moe(jax.random.PRNGKey(0), d_model=16, d_ff=32,
                          n_experts=8)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 10, 16))
        ref, ref_aux = moe_ffn(params, x, top_k=2, dtype=jnp.float32)
        fn, place = make_sharded_moe(mesh, "ep", top_k=2,
                                     dtype=jnp.float32)
        out, aux = fn(place(params), x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-5)
        # Experts really are sharded.
        assert len(place(params)["w_in"].sharding.device_set) == 8

    def test_differentiable(self):
        params = init_moe(jax.random.PRNGKey(0), d_model=8, d_ff=16,
                          n_experts=4)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 5, 8))

        def loss(p):
            out, aux = moe_ffn(p, x, top_k=2, dtype=jnp.float32)
            return jnp.sum(out ** 2) + 0.01 * aux

        grads = jax.grad(loss)(params)
        for leaf in jax.tree_util.tree_leaves(grads):
            assert np.isfinite(np.asarray(leaf)).all()
