"""Lock-hierarchy / cache-discipline linter tests (pkg/analysis/lint).

Two halves:
- per-rule unit tests over small synthetic modules (each rule must
  fire on its counterexample and stay quiet on the disciplined form);
- THE CI gate: the linter runs over the whole shipped package and must
  report zero non-baselined findings (real violations get fixed, not
  suppressed -- the committed baseline is empty and stays that way).
"""

import json
import os
import subprocess
import sys

import pytest

from k8s_dra_driver_gpu_tpu.pkg.analysis.lint import (
    RULES,
    Baseline,
    lint_source,
    metrics_exposition,
    run_lint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "k8s_dra_driver_gpu_tpu")
BASELINE = os.path.join(REPO, "analysis-baseline.json")


def rules_of(findings):
    return sorted({f.rule for f in findings})


class TestLockHierarchyRules:
    def test_out_of_order_acquisition_flagged(self):
        src = (
            "class S:\n"
            "    def bad(self):\n"
            "        with self._shards.hold([1]):\n"
            "            with self.pu_lock.acquire(timeout=1.0):\n"
            "                pass\n"
        )
        findings = lint_source(src)
        assert "TPUDRA001" in rules_of(findings)

    def test_documented_order_clean(self):
        src = (
            "class S:\n"
            "    def good(self):\n"
            "        with self.pu_lock.acquire(timeout=1.0):\n"
            "            with self._shards.hold([1]):\n"
            "                self._checkpoint.update_claim('u', None)\n"
        )
        assert lint_source(src) == []

    def test_checkpoint_call_under_locks_is_legal(self):
        # Level 3 inside level 1/2 is the documented direction.
        src = (
            "class S:\n"
            "    def good(self):\n"
            "        with self.pu_lock.acquire(timeout=1.0):\n"
            "            self._checkpoint.get()\n"
        )
        assert lint_source(src) == []

    def test_reentrant_flock_flagged(self):
        src = (
            "class S:\n"
            "    def bad(self):\n"
            "        with self.pu_lock.acquire(timeout=1.0):\n"
            "            with self.pu_lock.acquire(timeout=1.0):\n"
            "                pass\n"
        )
        findings = lint_source(src)
        assert "TPUDRA004" in rules_of(findings)

    def test_distinct_flocks_nested_clean(self):
        src = (
            "class S:\n"
            "    def good(self):\n"
            "        with self.a_lock.acquire(timeout=1.0):\n"
            "            with self.b_lock.acquire(timeout=1.0):\n"
            "                pass\n"
        )
        assert lint_source(src) == []


class TestBareAcquireRule:
    def test_discarded_acquire_flagged(self):
        src = (
            "def bad(lock):\n"
            "    lock.acquire(timeout=1.0)\n"
            "    do_work()\n"
            "    lock.release()\n"
        )
        findings = lint_source(src)
        assert "TPUDRA002" in rules_of(findings)

    def test_unrelated_release_in_finally_still_flagged(self):
        """An unrelated b.release() in a finally must not excuse a
        leaked a.acquire() -- the release must match the lock."""
        src = (
            "def bad(self):\n"
            "    self.a.acquire(timeout=1.0)\n"
            "    try:\n"
            "        do_work()\n"
            "    finally:\n"
            "        self.b.release()\n"
        )
        findings = lint_source(src)
        assert "TPUDRA002" in rules_of(findings)

    def test_release_in_finally_clean(self):
        src = (
            "def good(lock):\n"
            "    lock.acquire(timeout=1.0)\n"
            "    try:\n"
            "        do_work()\n"
            "    finally:\n"
            "        lock.release()\n"
        )
        assert lint_source(src) == []

    def test_with_guard_clean(self):
        src = (
            "def good(lock):\n"
            "    with lock.acquire(timeout=1.0):\n"
            "        do_work()\n"
        )
        assert lint_source(src) == []


class TestBlockingUnderLockRule:
    def test_kube_call_under_shard_lock_flagged(self):
        src = (
            "class S:\n"
            "    def bad(self):\n"
            "        with self._shards.hold([0]):\n"
            "            self.kube.patch('', 'v1', 'nodes', 'n', {})\n"
        )
        findings = lint_source(src)
        assert "TPUDRA003" in rules_of(findings)

    def test_sleep_under_flock_flagged(self):
        src = (
            "import time\n"
            "class S:\n"
            "    def bad(self):\n"
            "        with self.pu_lock.acquire(timeout=1.0):\n"
            "            time.sleep(5)\n"
        )
        findings = lint_source(src)
        assert "TPUDRA003" in rules_of(findings)

    def test_kube_call_outside_lock_clean(self):
        src = (
            "class S:\n"
            "    def good(self):\n"
            "        with self._shards.hold([0]):\n"
            "            x = 1\n"
            "        self.kube.patch('', 'v1', 'nodes', 'n', {})\n"
        )
        assert lint_source(src) == []


class TestStateLiteralRule:
    def test_raw_state_literal_flagged(self):
        src = "def f(c):\n    return c.state == 'PrepareStarted'\n"
        findings = lint_source(src, rel="kubeletplugin/cleanup.py")
        assert "TPUDRA005" in rules_of(findings)

    def test_enum_definition_site_exempt(self):
        src = "PREPARE_STARTED = 'PrepareStarted'\n"
        assert lint_source(src, rel="kubeletplugin/checkpoint.py") == []

    def test_raw_defrag_state_literal_flagged(self):
        """The defrag-move lifecycle literals (pkg/defrag.py) are
        fenced exactly like the prepare/eviction/partition states."""
        src = ("def f(rec):\n"
               "    return rec.state in ('DefragPlanned',"
               " 'DefragDraining', 'DefragDeallocated')\n")
        findings = lint_source(src, rel="pkg/defrag.py")
        assert sum(1 for f in findings if f.rule == "TPUDRA005") == 3

    def test_defrag_statemachine_definition_site_exempt(self):
        src = "DEFRAG_PLANNED = 'DefragPlanned'\n"
        assert lint_source(src, rel="pkg/analysis/statemachine.py") == []


class TestCachedObjectMutationRule:
    def test_mutating_kube_get_result_flagged(self):
        src = (
            "class S:\n"
            "    def bad(self):\n"
            "        obj = self.kube.get('g', 'v1', 'r', 'n')\n"
            "        obj['metadata']['labels'] = {}\n"
        )
        findings = lint_source(src)
        assert "TPUDRA006" in rules_of(findings)

    def test_mutating_informer_object_flagged(self):
        src = (
            "class S:\n"
            "    def bad(self):\n"
            "        cd = self._cd_informer.get_by_uid('u')\n"
            "        cd['status'].update({'x': 1})\n"
        )
        findings = lint_source(src)
        assert "TPUDRA006" in rules_of(findings)

    def test_mutating_api_shaped_param_flagged(self):
        # The controller.reconcile shape: an API object arrives as a
        # parameter and its metadata subtree is mutated in place.
        src = (
            "def reconcile(cd):\n"
            "    meta = cd['metadata']\n"
            "    meta.setdefault('finalizers', []).append('fin')\n"
        )
        findings = lint_source(src)
        assert "TPUDRA006" in rules_of(findings)

    def test_deep_copy_launders_taint(self):
        src = (
            "def good(cd):\n"
            "    meta = cd['metadata']\n"
            "    cd = json_copy(cd)\n"
            "    cd['metadata'].setdefault('finalizers', []).append('f')\n"
        )
        assert lint_source(src) == []

    def test_helper_returning_kube_objects_taints(self):
        src = (
            "class S:\n"
            "    def _pods(self):\n"
            "        return self.kube.list('', 'v1', 'pods')\n"
            "    def bad(self):\n"
            "        for pod in self._pods():\n"
            "            pod['status']['phase'] = 'Failed'\n"
        )
        findings = lint_source(src)
        assert "TPUDRA006" in rules_of(findings)

    def test_fresh_container_mutation_clean(self):
        src = (
            "def good(pod):\n"
            "    kept = [c for c in pod.get('status', {})"
            ".get('conditions') or []]\n"
            "    kept.append({'type': 'PodScheduled'})\n"
        )
        assert lint_source(src) == []


class TestCheckpointManagerRule:
    IMPORT = "from .checkpoint import CheckpointManager\n"

    def test_missing_policy_flagged(self):
        src = self.IMPORT + "cm = CheckpointManager(root, boot_id='b')\n"
        findings = lint_source(src, rel="kubeletplugin/device_state.py")
        assert "TPUDRA007" in rules_of(findings)

    def test_aliased_import_flagged(self):
        src = ("from ...kubeletplugin.checkpoint import "
               "CheckpointManager as CM\n"
               "cm = CM(root)\n")
        findings = lint_source(src, rel="computedomain/x.py")
        assert "TPUDRA007" in rules_of(findings)

    def test_policy_present_clean(self):
        src = (self.IMPORT
               + "cm = CheckpointManager(root, boot_id='b', "
                 "transition_policy=TWO_PHASE_POLICY)\n")
        assert lint_source(src, rel="kubeletplugin/device_state.py") == []

    def test_unrelated_same_named_class_not_flagged(self):
        # orbax's ocp.CheckpointManager (train/checkpoint.py) must not
        # trip the rule: scope is the name imported from the driver's
        # checkpoint module, not any class that happens to share it.
        src = ("import orbax.checkpoint as ocp\n"
               "mngr = ocp.CheckpointManager(directory)\n")
        assert lint_source(src, rel="train/anything.py") == []

    def test_module_attribute_construction_flagged(self):
        # `from ..kubeletplugin import checkpoint` then
        # `checkpoint.CheckpointManager(...)` must not slip the rule.
        src = ("from ..kubeletplugin import checkpoint\n"
               "cm = checkpoint.CheckpointManager(root)\n")
        findings = lint_source(src, rel="computedomain/x.py")
        assert "TPUDRA007" in rules_of(findings)

    def test_orbax_module_attribute_not_flagged(self):
        src = ("import orbax.checkpoint as ocp\n"
               "m = ocp.CheckpointManager('d')\n")
        assert lint_source(src, rel="train/checkpoint.py") == []

    def test_definition_module_not_flagged(self):
        # checkpoint.py DEFINES the class (no import): out of scope.
        src = "cm = CheckpointManager(root)\n"
        assert lint_source(src, rel="kubeletplugin/checkpoint.py") == []


class TestRawKubeClientRule:
    """TPUDRA008: raw KubeClient outside the RetryingKubeClient wrap,
    and kube verbs on a raw client without an explicit timeout."""

    def test_raw_construction_flagged(self):
        src = ("def main():\n"
               "    kube = KubeClient(host='https://x')\n")
        findings = lint_source(src, rel="pkg/somewhere.py")
        assert "TPUDRA008" in rules_of(findings)

    def test_wrapped_construction_clean(self):
        src = ("def main():\n"
               "    kube = RetryingKubeClient(KubeClient(host='x'))\n")
        assert lint_source(src, rel="pkg/somewhere.py") == []

    def test_conditional_fake_or_real_inside_wrapper_clean(self):
        # The standard main-entry shape: the wrapper sanctions every
        # ctor anywhere inside its argument tree.
        src = ("def main(standalone):\n"
               "    kube = RetryingKubeClient(\n"
               "        FakeKubeClient() if standalone else KubeClient())\n")
        assert lint_source(src, rel="pkg/somewhere.py") == []

    def test_from_kubeconfig_flagged(self):
        src = ("def main():\n"
               "    kube = KubeClient.from_kubeconfig('/tmp/kc')\n")
        findings = lint_source(src, rel="pkg/somewhere.py")
        assert "TPUDRA008" in rules_of(findings)

    def test_fake_client_not_flagged(self):
        src = ("def main():\n"
               "    kube = FakeKubeClient()\n"
               "    kube.get('', 'v1', 'pods', 'p')\n")
        assert lint_source(src, rel="pkg/somewhere.py") == []

    def test_verb_without_timeout_on_raw_client_flagged(self):
        src = ("def main():\n"
               "    kube = KubeClient()\n"
               "    kube.list('', 'v1', 'pods')\n")
        findings = lint_source(src, rel="pkg/somewhere.py")
        eights = [f for f in findings if f.rule == "TPUDRA008"]
        assert len(eights) == 2  # the ctor AND the timeout-less verb

    def test_verb_with_timeout_on_raw_client_single_finding(self):
        src = ("def main():\n"
               "    kube = KubeClient()\n"
               "    kube.list('', 'v1', 'pods', timeout=5.0)\n")
        findings = lint_source(src, rel="pkg/somewhere.py")
        eights = [f for f in findings if f.rule == "TPUDRA008"]
        assert len(eights) == 1  # only the raw ctor

    def test_definition_modules_exempt(self):
        src = "client = KubeClient(host='x')\n"
        assert lint_source(src, rel="pkg/kubeclient.py") == []
        assert lint_source(src, rel="pkg/retry.py") == []
        assert "TPUDRA008" in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))


class TestSuppression:
    SRC_BAD = "def bad(lock):\n    lock.acquire(timeout=1.0)\n"

    def test_inline_allow_same_line(self):
        src = ("def bad(lock):\n"
               "    lock.acquire(timeout=1.0)  # tpudra: allow=TPUDRA002\n")
        assert lint_source(src) == []

    def test_inline_allow_previous_comment_line(self):
        src = ("def bad(lock):\n"
               "    # guard object owns release; tpudra: allow=TPUDRA002\n"
               "    lock.acquire(timeout=1.0)\n")
        assert lint_source(src) == []

    def test_inline_allow_wrong_rule_still_fires(self):
        src = ("def bad(lock):\n"
               "    lock.acquire(timeout=1.0)  # tpudra: allow=TPUDRA003\n")
        assert rules_of(lint_source(src)) == ["TPUDRA002"]

    def test_file_allow(self):
        src = ("# server-side fake; tpudra: allow-file=TPUDRA002\n"
               + self.SRC_BAD)
        assert lint_source(src) == []

    def test_file_allow_honored_anywhere_in_header(self):
        # allow-file= may sit on any of the first 10 lines (module
        # docstrings and imports routinely precede it).
        src = ("# line 1\n" * 8
               + "# server-side fake; tpudra: allow-file=TPUDRA002\n"
               + self.SRC_BAD)
        assert lint_source(src) == []

    def test_file_allow_beyond_header_ignored(self):
        """The ISSUE 18 satellite: a file-wide pragma buried past the
        first 10 lines (where nobody reviewing the module header would
        see it) must NOT disable the rule."""
        src = ("# line 1\n" * 10
               + "# sneaky; tpudra: allow-file=TPUDRA002\n"
               + self.SRC_BAD)
        assert rules_of(lint_source(src)) == ["TPUDRA002"]

    def test_file_allow_in_trailing_string_literal_ignored(self):
        # The header restriction also means a string LITERAL deep in
        # the module carrying the pragma text can't disable a rule
        # (pre-restriction, scanning the whole source let it).
        src = (self.SRC_BAD
               + "    pass\n" * 9
               + "    x = '# tpudra: allow-file=TPUDRA002'\n")
        assert "TPUDRA002" in rules_of(lint_source(src))

    def test_multiple_allow_groups_on_one_line(self):
        # Stacked suppressions, each with its own reason comment: every
        # `tpudra: allow=` group on the line is honored (finditer, not
        # a first-match search).
        src = ("import time\n"
               "class S:\n"
               "    def bad(self):\n"
               "        with self.pu_lock.acquire(timeout=1.0):\n"
               "            time.sleep(1)"
               "  # fake clock: tpudra: allow=TPUDRA003"
               "  # bounded: tpudra: allow=TPUDRA999\n")
        assert lint_source(src) == []
        # ... and order doesn't matter: the matching rule may be the
        # first group just as well.
        src2 = src.replace("allow=TPUDRA003", "allow=TPUDRA998").replace(
            "allow=TPUDRA999", "allow=TPUDRA003")
        assert lint_source(src2) == []

    def test_comma_list_allow(self):
        src = ("def bad(lock):\n"
               "    lock.acquire(timeout=1.0)"
               "  # tpudra: allow=TPUDRA001,TPUDRA002\n")
        assert lint_source(src) == []

    def test_crlf_source_findings_and_suppressions(self):
        """CRLF line endings must not break line-table indexing: the
        finding still fires on the right line, and the suppression
        comment (whose line now ends in \\r) still matches."""
        bad = self.SRC_BAD.replace("\n", "\r\n")
        assert rules_of(lint_source(bad)) == ["TPUDRA002"]
        allowed = ("def bad(lock):\r\n"
                   "    lock.acquire(timeout=1.0)"
                   "  # tpudra: allow=TPUDRA002\r\n")
        assert lint_source(allowed) == []
        header = ("# fake; tpudra: allow-file=TPUDRA002\r\n"
                  + bad)
        assert lint_source(header) == []

    def test_crlf_file_through_run_lint(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_bytes(
            b"def bad(lock):\r\n    lock.acquire(timeout=1.0)\r\n")
        report = run_lint([str(mod)], root=str(tmp_path))
        assert [f.rule for f in report.findings] == ["TPUDRA002"]
        assert report.findings[0].line == 2

    def test_baseline_fingerprint_is_line_number_free(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(self.SRC_BAD)
        report = run_lint([str(mod)], root=str(tmp_path))
        (fp,) = [f.fingerprint for f in report.findings]
        baseline = Baseline({fp: "known"}, path=str(tmp_path / "b.json"))
        # Shift the finding by 5 lines: the fingerprint must not move.
        mod.write_text("# pad\n" * 5 + self.SRC_BAD)
        report2 = run_lint([str(mod)], baseline=baseline,
                           root=str(tmp_path))
        assert [f.fingerprint for f in report2.findings] == [fp]
        assert report2.active == [] and len(report2.baselined) == 1


class TestRunnerAndOutput:
    def test_json_output_mode(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text("def bad(lock):\n    lock.acquire(timeout=1.0)\n")
        proc = subprocess.run(
            [sys.executable, "-m", "k8s_dra_driver_gpu_tpu.pkg.analysis",
             str(mod), "--root", str(tmp_path), "--no-baseline", "--json"],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["counts"]["TPUDRA002"] == 1
        assert doc["findings"][0]["rule"] == "TPUDRA002"
        assert set(doc["rules"]) == set(RULES)

    def test_metrics_exposition(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text("def bad(lock):\n    lock.acquire(timeout=1.0)\n")
        report = run_lint([str(mod)], root=str(tmp_path))
        text = metrics_exposition(report)
        assert 'tpu_dra_lint_findings_total{rule="TPUDRA002"} 1' in text
        assert 'tpu_dra_lint_findings_total{rule="TPUDRA001"} 0' in text

    def test_bench_lint_summary_shape(self):
        import bench

        out = bench.bench_lint_findings()
        assert out["lint_findings_total"] == 0
        assert out["lint_findings_baselined"] == 0

    def test_update_baseline_roundtrip_and_prunes_stale(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text("def bad(lock):\n    lock.acquire(timeout=1.0)\n")
        bl_path = tmp_path / "baseline.json"
        env = {**os.environ, "PYTHONPATH": REPO}
        args = [sys.executable, "-m",
                "k8s_dra_driver_gpu_tpu.pkg.analysis", str(mod),
                "--root", str(tmp_path), "--baseline", str(bl_path)]
        proc = subprocess.run(args + ["--update-baseline"],
                              capture_output=True, text=True, cwd=REPO,
                              env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        proc = subprocess.run(args, capture_output=True, text=True,
                              cwd=REPO, env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        # Fix the violation at the source: re-updating must PRUNE the
        # stale fingerprint, or a reintroduced same-shaped defect would
        # be silently suppressed forever.
        mod.write_text(
            "def good(lock):\n"
            "    with lock.acquire(timeout=1.0):\n"
            "        pass\n")
        proc = subprocess.run(args + ["--update-baseline"],
                              capture_output=True, text=True, cwd=REPO,
                              env=env)
        assert proc.returncode == 0 and "1 stale pruned" in proc.stdout
        assert json.load(open(bl_path))["suppressions"] == {}

    def test_syntax_error_reported_not_crash(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text("def broken(:\n")
        report = run_lint([str(mod)], root=str(tmp_path))
        assert [f.rule for f in report.findings] == ["TPUDRA000"]
        # TPUDRA000 is a cataloged rule: the CLI summary, counts() and
        # the metrics exposition must all carry it (a syntax error in a
        # linted tree once crashed the summary loop with a KeyError).
        assert "TPUDRA000" in RULES
        assert report.counts()["TPUDRA000"] == 1
        assert ('tpu_dra_lint_findings_total{rule="TPUDRA000"} 1'
                in metrics_exposition(report))
        proc = subprocess.run(
            [sys.executable, "-m", "k8s_dra_driver_gpu_tpu.pkg.analysis",
             str(mod), "--root", str(tmp_path), "--no-baseline"],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "TPUDRA000" in proc.stdout
        assert "Traceback" not in proc.stderr

    def test_same_shaped_findings_get_distinct_fingerprints(
            self, tmp_path):
        """One baseline entry must never blanket-suppress a FUTURE
        same-shaped finding in the same function."""
        mod = tmp_path / "m.py"
        mod.write_text(
            "def bad(self):\n"
            "    obj = self.kube.get('g', 'v1', 'r', 'n')\n"
            "    obj['metadata']['labels'] = {}\n"
            "    obj['metadata']['annotations'] = {}\n"
        )
        report = run_lint([str(mod)], root=str(tmp_path))
        fps = [f.fingerprint for f in report.findings
               if f.rule == "TPUDRA006"]
        assert len(fps) == 2 and len(set(fps)) == 2, fps
        # Baselining only the first leaves the second active.
        baseline = Baseline({fps[0]: "known"})
        report2 = run_lint([str(mod)], baseline=baseline,
                           root=str(tmp_path))
        active = [f.fingerprint for f in report2.active
                  if f.rule == "TPUDRA006"]
        assert active == [fps[1]]


class TestFingerprintSuffixCollisions:
    """ISSUE 18 satellite: edge cases of the #N fingerprint-suffix
    disambiguator around the baseline grammar."""

    def test_three_same_shaped_findings_all_distinct(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(
            "def bad(self):\n"
            "    obj = self.kube.get('g', 'v1', 'r', 'n')\n"
            "    obj['metadata']['labels'] = {}\n"
            "    obj['metadata']['annotations'] = {}\n"
            "    obj['metadata']['finalizers'] = []\n"
        )
        report = run_lint([str(mod)], root=str(tmp_path))
        fps = [f.fingerprint for f in report.findings
               if f.rule == "TPUDRA006"]
        assert len(fps) == 3 and len(set(fps)) == 3
        # Baselining #1 and #3 leaves exactly #2 active.
        baseline = Baseline({fps[0]: "known", fps[2]: "known"})
        report2 = run_lint([str(mod)], baseline=baseline,
                           root=str(tmp_path))
        assert [f.fingerprint for f in report2.active
                if f.rule == "TPUDRA006"] == [fps[1]]

    def test_suffix_counter_scoped_per_function(self, tmp_path):
        # The SAME shape in two different functions needs no #N suffix
        # (the qualname already splits them) -- and the fingerprints
        # must still be distinct.
        mod = tmp_path / "m.py"
        mod.write_text(
            "def bad_a(self):\n"
            "    obj = self.kube.get('g', 'v1', 'r', 'n')\n"
            "    obj['metadata']['labels'] = {}\n"
            "def bad_b(self):\n"
            "    obj = self.kube.get('g', 'v1', 'r', 'n')\n"
            "    obj['metadata']['labels'] = {}\n"
        )
        report = run_lint([str(mod)], root=str(tmp_path))
        fps = [f.fingerprint for f in report.findings
               if f.rule == "TPUDRA006"]
        assert len(fps) == 2 and len(set(fps)) == 2
        assert not any("#" in fp.rsplit(":", 1)[-1] for fp in fps)

    def test_suffixed_fingerprints_survive_line_shifts(self, tmp_path):
        # The whole point of key-based fingerprints, extended to the
        # suffixed ones: moving the function must not re-key #2.
        mod = tmp_path / "m.py"
        body = ("def bad(self):\n"
                "    obj = self.kube.get('g', 'v1', 'r', 'n')\n"
                "    obj['metadata']['labels'] = {}\n"
                "    obj['metadata']['annotations'] = {}\n")
        mod.write_text(body)
        fps1 = [f.fingerprint for f in
                run_lint([str(mod)], root=str(tmp_path)).findings]
        mod.write_text("# pad\n" * 7 + body)
        fps2 = [f.fingerprint for f in
                run_lint([str(mod)], root=str(tmp_path)).findings]
        assert fps1 == fps2


class TestInterproceduralLockRule:
    """TPUDRA017: kube I/O / sleep reached TRANSITIVELY through the
    project call graph while a hierarchy lock is held. Direct sinks
    stay TPUDRA003/010's beat."""

    def test_helper_method_kube_io_under_state_lock_flagged(self):
        src = ("class DraScheduler:\n"
               "    def _publish(self, name):\n"
               "        self.kube.patch('', 'v1', 'pods', name, {})\n"
               "    def bad(self, name):\n"
               "        with self._state_lock:\n"
               "            self._publish(name)\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        hits = [f for f in findings if f.rule == "TPUDRA017"]
        assert len(hits) == 1
        # The finding carries the witness edge chain down to the sink.
        assert hits[0].edge is not None
        assert "_publish" in hits[0].edge
        assert "kube.patch" in hits[0].edge

    def test_two_hop_sleep_under_flock_flagged(self):
        src = ("import time\n"
               "def deep():\n"
               "    time.sleep(1)\n"
               "def mid():\n"
               "    deep()\n"
               "class S:\n"
               "    def bad(self):\n"
               "        with self.pu_lock.acquire(timeout=1.0):\n"
               "            mid()\n")
        findings = lint_source(src, rel="kubeletplugin/x.py")
        hits = [f for f in findings if f.rule == "TPUDRA017"]
        assert len(hits) == 1
        assert "mid" in hits[0].edge and "deep" in hits[0].edge
        assert "time.sleep" in hits[0].edge

    def test_direct_sink_stays_tpudra010_not_017(self):
        src = ("class DraScheduler:\n"
               "    def bad(self):\n"
               "        with self._state_lock:\n"
               "            self.kube.patch('', 'v1', 'pods', 'p', {})\n")
        rules = rules_of(lint_source(src, rel="pkg/scheduler.py"))
        assert "TPUDRA010" in rules and "TPUDRA017" not in rules

    def test_helper_call_outside_lock_clean(self):
        src = ("class DraScheduler:\n"
               "    def _publish(self, name):\n"
               "        self.kube.patch('', 'v1', 'pods', name, {})\n"
               "    def good(self, name):\n"
               "        with self._state_lock:\n"
               "            x = 1\n"
               "        self._publish(name)\n")
        assert "TPUDRA017" not in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))

    def test_nonblocking_helper_under_lock_clean(self):
        src = ("class DraScheduler:\n"
               "    def _bump(self, d):\n"
               "        d['n'] = d.get('n', 0) + 1\n"
               "    def good(self):\n"
               "        with self._state_lock:\n"
               "            self._bump(self._counters)\n")
        assert "TPUDRA017" not in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))

    def test_commit_io_helper_under_node_locks_sanctioned(self):
        # Same carve-out as TPUDRA010: per-node commit locks sanction
        # commit I/O, including transitively.
        src = ("class DraScheduler:\n"
               "    def _commit(self, name):\n"
               "        self.kube.patch('resource.k8s.io', 'v1',\n"
               "                        'resourceclaims', name, {})\n"
               "    def good(self, node, name):\n"
               "        with self._node_locks.hold((node,)):\n"
               "            self._commit(name)\n")
        assert "TPUDRA017" not in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))


class TestLaunderedMutationRule:
    """TPUDRA016: an informer-cached / API object handed to a
    CROSS-MODULE helper that writes through the parameter -- the
    mutation TPUDRA006's intra-module taint pass can't see."""

    HELPER = ("def set_label(obj, v):\n"
              "    obj['metadata']['labels'] = v\n")

    def _lint_pair(self, tmp_path, caller_src):
        (tmp_path / "helpers.py").write_text(self.HELPER)
        (tmp_path / "caller.py").write_text(caller_src)
        report = run_lint([str(tmp_path)], root=str(tmp_path))
        return report.findings

    def test_tainted_object_to_mutating_helper_flagged(self, tmp_path):
        findings = self._lint_pair(
            tmp_path,
            "from helpers import set_label\n"
            "class S:\n"
            "    def bad(self):\n"
            "        pod = self.kube.get('', 'v1', 'pods', 'p')\n"
            "        set_label(pod, {})\n")
        hits = [f for f in findings if f.rule == "TPUDRA016"]
        assert len(hits) == 1
        assert hits[0].path == "caller.py"
        assert "set_label" in hits[0].edge
        assert "'obj'" in hits[0].edge  # the mutated parameter

    def test_copy_at_call_site_clean(self, tmp_path):
        findings = self._lint_pair(
            tmp_path,
            "import copy\n"
            "from helpers import set_label\n"
            "class S:\n"
            "    def good(self):\n"
            "        pod = self.kube.get('', 'v1', 'pods', 'p')\n"
            "        set_label(copy.deepcopy(pod), {})\n")
        assert "TPUDRA016" not in {f.rule for f in findings}

    def test_untainted_object_clean(self, tmp_path):
        findings = self._lint_pair(
            tmp_path,
            "from helpers import set_label\n"
            "class S:\n"
            "    def good(self):\n"
            "        fresh = {'metadata': {}}\n"
            "        set_label(fresh, {})\n")
        assert "TPUDRA016" not in {f.rule for f in findings}

    def test_same_module_helper_not_016(self):
        # Same-module laundering is the intra-module taint pass's job
        # (and a single-module graph never crosses rel boundaries).
        src = ("def set_label(obj, v):\n"
               "    obj['metadata']['labels'] = v\n"
               "class S:\n"
               "    def f(self):\n"
               "        pod = self.kube.get('', 'v1', 'pods', 'p')\n"
               "        set_label(pod, {})\n")
        assert "TPUDRA016" not in rules_of(
            lint_source(src, rel="pkg/x.py"))

    def test_non_mutating_helper_clean(self, tmp_path):
        (tmp_path / "helpers.py").write_text(
            "def label_of(obj):\n"
            "    return obj.get('metadata', {}).get('labels')\n")
        (tmp_path / "caller.py").write_text(
            "from helpers import label_of\n"
            "class S:\n"
            "    def good(self):\n"
            "        pod = self.kube.get('', 'v1', 'pods', 'p')\n"
            "        return label_of(pod)\n")
        report = run_lint([str(tmp_path)], root=str(tmp_path))
        assert "TPUDRA016" not in {f.rule for f in report.findings}


class TestCommitProtocolWriteRule:
    """TPUDRA018: a function coupling AllocationState.try_commit with
    a kube write to resourceclaims must ride a resourceVersion
    precondition on the write -- the 409 arbiter is what stops two
    active-active schedulers from double-allocating (the model
    checker's seeded bug, pinned statically)."""

    def test_commit_scope_write_without_rv_flagged(self):
        src = ("class S:\n"
               "    def commit(self, claim, cand):\n"
               "        if not self.alloc.try_commit(claim, cand):\n"
               "            return\n"
               "        self.kube.patch('resource.k8s.io', 'v1',\n"
               "                        'resourceclaims', 'c',\n"
               "                        {'status': {}})\n")
        findings = lint_source(src)
        hits = [f for f in findings if f.rule == "TPUDRA018"]
        assert len(hits) == 1
        assert "resourceVersion" in hits[0].message

    def test_rv_literal_anywhere_in_function_clean(self):
        # The precondition may be assembled AFTER the call in source
        # order (judged at function close, not at the call site).
        src = ("class S:\n"
               "    def commit(self, claim, cand):\n"
               "        if not self.alloc.try_commit(claim, cand):\n"
               "            return\n"
               "        body = {'metadata': {'resourceVersion':\n"
               "                claim['metadata']['resourceVersion']}}\n"
               "        self.kube.patch('resource.k8s.io', 'v1',\n"
               "                        'resourceclaims', 'c', body)\n")
        assert "TPUDRA018" not in rules_of(lint_source(src))

    def test_update_verb_also_fenced(self):
        src = ("class S:\n"
               "    def commit(self, claim, cand, obj):\n"
               "        if not self.alloc.try_commit(claim, cand):\n"
               "            return\n"
               "        self.kube.update('resource.k8s.io', 'v1',\n"
               "                         'resourceclaims', 'c', obj)\n")
        assert "TPUDRA018" in rules_of(lint_source(src))

    def test_claim_write_without_commit_scope_clean(self):
        # Status publishes outside the reservation protocol (e.g. the
        # drain's idempotent stamps) are not in scope.
        src = ("class S:\n"
               "    def publish(self, body):\n"
               "        self.kube.patch('resource.k8s.io', 'v1',\n"
               "                        'resourceclaims', 'c', body)\n")
        assert "TPUDRA018" not in rules_of(lint_source(src))

    def test_commit_scope_other_resource_clean(self):
        src = ("class S:\n"
               "    def commit(self, claim, cand):\n"
               "        if not self.alloc.try_commit(claim, cand):\n"
               "            return\n"
               "        self.kube.patch('', 'v1', 'nodes', 'n', {})\n")
        assert "TPUDRA018" not in rules_of(lint_source(src))


class TestDocUrlsAndEdges:
    """ISSUE 18 satellite: --json emits per-rule doc URLs and, for
    interprocedural findings, the resolved call-graph edge."""

    SRC_017 = ("import time\n"
               "def deep():\n"
               "    time.sleep(1)\n"
               "class S:\n"
               "    def bad(self):\n"
               "        with self.pu_lock.acquire(timeout=1.0):\n"
               "            deep()\n")

    def test_finding_dict_carries_doc_url_and_edge(self):
        from k8s_dra_driver_gpu_tpu.pkg.analysis.lint import rule_doc_url

        (hit,) = [f for f in lint_source(self.SRC_017)
                  if f.rule == "TPUDRA017"]
        d = hit.to_dict()
        assert d["doc_url"] == "docs/analysis.md#tpudra017"
        assert d["doc_url"] == rule_doc_url("TPUDRA017")
        assert "time.sleep" in d["edge"]
        # Non-interprocedural findings carry edge=None, not a miss.
        (two,) = lint_source(TestSuppression.SRC_BAD)
        assert two.to_dict()["edge"] is None
        assert two.to_dict()["doc_url"].endswith("#tpudra002")

    def test_doc_base_env_override(self, monkeypatch):
        from k8s_dra_driver_gpu_tpu.pkg.analysis.lint import rule_doc_url

        monkeypatch.setenv("TPU_DRA_ANALYSIS_DOC_BASE",
                           "https://ci.example.com/analysis")
        assert rule_doc_url("TPUDRA018") == \
            "https://ci.example.com/analysis#tpudra018"

    def test_json_cli_emits_rule_docs_and_edges(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(self.SRC_017)
        proc = subprocess.run(
            [sys.executable, "-m", "k8s_dra_driver_gpu_tpu.pkg.analysis",
             str(mod), "--root", str(tmp_path), "--no-baseline", "--json"],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert set(doc["rule_docs"]) == set(RULES)
        assert doc["rule_docs"]["TPUDRA017"] == \
            "docs/analysis.md#tpudra017"
        (f017,) = [f for f in doc["findings"]
                   if f["rule"] == "TPUDRA017"]
        assert "time.sleep" in f017["edge"]
        assert f017["doc_url"] == "docs/analysis.md#tpudra017"

    def test_text_mode_prints_witness_edge(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(self.SRC_017)
        proc = subprocess.run(
            [sys.executable, "-m", "k8s_dra_driver_gpu_tpu.pkg.analysis",
             str(mod), "--root", str(tmp_path), "--no-baseline"],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert proc.returncode == 1
        assert "via " in proc.stdout and "time.sleep" in proc.stdout


class TestSchedulerSyncListRule:
    """TPUDRA009: scheduler sync paths must read watched resources
    through the informer-backed ClusterView/snapshot (pkg/schedcache),
    never via a raw kube.list."""

    def test_raw_list_of_watched_resource_flagged(self):
        src = ("class DraScheduler:\n"
               "    def _allocate_claims(self):\n"
               "        return self.kube.list('resource.k8s.io', 'v1',\n"
               "                              'resourceclaims')\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA009" in rules_of(findings)

    def test_starred_resource_tuple_still_flagged(self):
        # The common call shape: self.kube.list(*RESOURCE, "pods").
        src = ("class DraScheduler:\n"
               "    def _pods(self):\n"
               "        return self.kube.list(*RESOURCE, 'pods')\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA009" in rules_of(findings)

    def test_view_reads_clean(self):
        src = ("class DraScheduler:\n"
               "    def _pods(self):\n"
               "        return self.view.pods()\n")
        assert lint_source(src, rel="pkg/scheduler.py") == []

    def test_unwatched_resource_clean(self):
        src = ("class DraScheduler:\n"
               "    def _events(self):\n"
               "        return self.kube.list('', 'v1', 'events')\n")
        assert lint_source(src, rel="pkg/scheduler.py") == []

    def test_other_files_out_of_scope(self):
        # schedcache.py IS the sanctioned listing layer.
        src = ("class ClusterView:\n"
               "    def pods(self):\n"
               "        return self.kube.list('', 'v1', 'pods')\n")
        assert "TPUDRA009" not in rules_of(
            lint_source(src, rel="pkg/schedcache.py"))


class TestSnapshotInternalMutationFence:
    """TPUDRA009 extension (PR 11): per-pool sub-snapshot internals
    (pkg/schedcache PoolSnapshot / merged-view indexes + memos) are
    shared BY IDENTITY across snapshot generations, so they may only
    be mutated from schedcache.py's delta paths."""

    def test_subscript_write_flagged(self):
        src = ("def bad(snap, key, val):\n"
               "    snap.order_cache[key] = val\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA009" in rules_of(findings)

    def test_mutator_call_flagged(self):
        src = ("def bad(snap, cand):\n"
               "    snap.candidates.append(cand)\n")
        findings = lint_source(src, rel="pkg/recovery.py")
        assert "TPUDRA009" in rules_of(findings)

    def test_attribute_rebind_flagged(self):
        src = ("def bad(snap):\n"
               "    snap.by_key = {}\n")
        findings = lint_source(src, rel="pkg/fleetstate.py")
        assert "TPUDRA009" in rules_of(findings)

    def test_del_flagged(self):
        src = ("def bad(snap, key):\n"
               "    del snap.by_node[key]\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA009" in rules_of(findings)

    def test_augmented_attribute_write_flagged(self):
        src = ("def bad(snap, more):\n"
               "    snap.order_cache |= more\n"
               "    snap.candidates += [1]\n")
        findings = [f for f in lint_source(src, rel="pkg/scheduler.py")
                    if f.rule == "TPUDRA009"]
        assert len(findings) == 2

    def test_schedcache_delta_paths_sanctioned(self):
        src = ("def delta(snap, key, val):\n"
               "    snap.by_key[key] = val\n"
               "    snap.order_cache.pop(key, None)\n")
        assert "TPUDRA009" not in rules_of(
            lint_source(src, rel="pkg/schedcache.py"))

    def test_stray_schedcache_basename_not_sanctioned(self):
        # Rel-path suffix matched, not basename (the TPUDRA011
        # lesson): a stray schedcache.py elsewhere gets no pass.
        src = ("def bad(snap, key, val):\n"
               "    snap.by_key[key] = val\n")
        findings = lint_source(src, rel="other/dir/my_schedcache.py")
        assert "TPUDRA009" in rules_of(findings)

    def test_reads_and_own_attrs_clean(self):
        src = ("class Other:\n"
               "    def __init__(self):\n"
               "        self.by_node = {}\n"  # its OWN attribute
               "    def read(self, snap, node):\n"
               "        return snap.by_node.get(node, ())\n")
        assert "TPUDRA009" not in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))

    def test_order_memo_accessors_clean(self):
        src = ("def topo(snap, key, val):\n"
               "    hit = snap.order_memo_get(key)\n"
               "    snap.order_memo_put(key, val)\n")
        assert "TPUDRA009" not in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))


class TestSchedulerLockDisciplineRule:
    """TPUDRA010 + the sharded-allocation lock hierarchy: kube I/O is
    forbidden under the scheduler registry (_state_lock) and
    allocation-state (_alloc_lock) locks, sanctioned under the
    per-node locks, and the node locks sit OUTSIDE both in the
    documented order."""

    def test_kube_patch_under_state_lock_flagged(self):
        src = ("class DraScheduler:\n"
               "    def bad(self):\n"
               "        with self._state_lock:\n"
               "            self.kube.patch('', 'v1', 'pods', 'p', {})\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA010" in rules_of(findings)

    def test_kube_get_under_alloc_lock_flagged(self):
        src = ("class AllocationState:\n"
               "    def bad(self):\n"
               "        with self._alloc_lock:\n"
               "            self.kube.get('', 'v1', 'pods', 'p')\n")
        findings = lint_source(src, rel="pkg/schedcache.py")
        assert "TPUDRA010" in rules_of(findings)

    def test_sleep_under_state_lock_flagged(self):
        src = ("import time\n"
               "class DraScheduler:\n"
               "    def bad(self):\n"
               "        with self._state_lock:\n"
               "            time.sleep(1)\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA010" in rules_of(findings)

    def test_commit_io_under_node_locks_sanctioned(self):
        src = ("class DraScheduler:\n"
               "    def good(self, node):\n"
               "        with self._node_locks.hold((node,)):\n"
               "            self.kube.patch('resource.k8s.io', 'v1',\n"
               "                            'resourceclaims', 'c', {})\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA010" not in rules_of(findings)

    def test_bookkeeping_under_state_lock_clean(self):
        src = ("class DraScheduler:\n"
               "    def good(self):\n"
               "        with self._state_lock:\n"
               "            self._commit_log.pop(('ns', 'n'), None)\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA010" not in rules_of(findings)

    def test_node_lock_inside_state_lock_is_inversion(self):
        # Documented order: node locks -> _state_lock -> _alloc_lock.
        src = ("class DraScheduler:\n"
               "    def bad(self, node):\n"
               "        with self._state_lock:\n"
               "            with self._node_locks.hold((node,)):\n"
               "                pass\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA001" in rules_of(findings)

    def test_documented_sched_order_clean(self):
        src = ("class DraScheduler:\n"
               "    def good(self, node):\n"
               "        with self._node_locks.hold((node,)):\n"
               "            with self._state_lock:\n"
               "                with self._alloc_lock:\n"
               "                    pass\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA001" not in rules_of(findings)


class TestCarveOutRegistryRule:
    """TPUDRA011: carve-out registry create/destroy is sanctioned only
    in the partition engine and DeviceState -- everything else must go
    through PartitionEngine.attach/detach or the prepare pipeline."""

    def test_registry_create_elsewhere_flagged(self):
        src = ("class Sweeper:\n"
               "    def bad(self, live):\n"
               "        self._registry.create(live)\n")
        findings = lint_source(src, rel="kubeletplugin/reconcile.py")
        assert "TPUDRA011" in rules_of(findings)

    def test_registry_destroy_via_public_alias_flagged(self):
        src = ("def reap(state, uuid):\n"
               "    state.subslice_registry.destroy(uuid)\n")
        findings = lint_source(src, rel="pkg/recovery.py")
        assert "TPUDRA011" in rules_of(findings)

    def test_device_state_sanctioned(self):
        src = ("class DeviceState:\n"
               "    def _rollback(self, uuid):\n"
               "        self._registry.destroy(uuid)\n")
        assert "TPUDRA011" not in rules_of(
            lint_source(src, rel="kubeletplugin/device_state.py"))

    def test_partition_engine_sanctioned_by_rel_path(self):
        src = ("class PartitionEngine:\n"
               "    def attach(self, live):\n"
               "        self._state.subslice_registry.create(live)\n")
        assert "TPUDRA011" not in rules_of(
            lint_source(src, rel="pkg/partition/engine.py"))

    def test_same_basename_elsewhere_not_sanctioned(self):
        # A stray engine.py outside pkg/partition/ gets no free pass.
        src = ("def hack(state, live):\n"
               "    state.subslice_registry.create(live)\n")
        findings = lint_source(src, rel="pkg/other/engine.py")
        assert "TPUDRA011" in rules_of(findings)

    def test_registry_reads_clean(self):
        src = ("def audit(state):\n"
               "    return state.subslice_registry.list()\n")
        assert "TPUDRA011" not in rules_of(
            lint_source(src, rel="pkg/recovery.py"))

    def test_unrelated_create_clean(self):
        src = ("def mk(kube, obj):\n"
               "    kube.create('', 'v1', 'pods', obj)\n")
        assert "TPUDRA011" not in rules_of(
            lint_source(src, rel="pkg/recovery.py"))

    def test_out_of_scope_files_unaffected(self):
        # A _state_lock-named mutex elsewhere is not the scheduler's.
        src = ("class Other:\n"
               "    def fine(self):\n"
               "        with self._state_lock:\n"
               "            self.kube.patch('', 'v1', 'pods', 'p', {})\n")
        findings = lint_source(src, rel="kubeletplugin/other.py")
        assert "TPUDRA010" not in rules_of(findings)


class TestSpanDisciplineRule:
    """TPUDRA012: spans and flight-recorder entries go through the
    public with-guarded APIs. Bare Span / FlightEvent construction and
    a start_span held outside `with` leak unfinished spans (never
    exported, mis-parented children) or bypass the ring's locking."""

    def test_bare_span_ctor_flagged(self):
        src = ("from .tracing import Span, SpanContext\n"
               "def bad(ctx):\n"
               "    sp = Span('prep', ctx)\n"
               "    return sp\n")
        findings = lint_source(src, rel="pkg/recovery.py")
        assert "TPUDRA012" in rules_of(findings)

    def test_bare_flight_event_ctor_flagged(self):
        src = ("from .flightrecorder import FlightEvent\n"
               "def bad(uid):\n"
               "    return FlightEvent(ts=0.0, key=uid, event='x')\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA012" in rules_of(findings)

    def test_start_span_outside_with_flagged(self):
        src = ("from . import tracing\n"
               "def bad():\n"
               "    sp = tracing.start_span('op')\n"
               "    return sp\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA012" in rules_of(findings)

    def test_public_span_outside_with_flagged(self):
        # The public span() helper held outside `with` is the same
        # unfinished-span leak under the other spelling.
        src = ("from . import tracing\n"
               "def bad():\n"
               "    sp = tracing.span('op')\n"
               "    return sp\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA012" in rules_of(findings)

    def test_other_objects_span_method_clean(self):
        # Only bare span( / tracing.span( are fenced; a same-named
        # method on some other object never trips the rule.
        src = ("def good(doc):\n"
               "    return doc.span('header')\n")
        assert "TPUDRA012" not in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))

    def test_with_guarded_span_clean(self):
        src = ("from . import tracing\n"
               "def good(uid):\n"
               "    with tracing.span('op', attrs={'claim_uid': uid}):\n"
               "        pass\n")
        assert "TPUDRA012" not in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))

    def test_start_span_as_with_context_clean(self):
        # `with start_span(...)` IS finished on every path -- the
        # with-guard is the discipline, not the helper's name.
        src = ("from . import tracing\n"
               "def good():\n"
               "    with tracing.start_span('op') as sp:\n"
               "        return sp.context\n")
        assert "TPUDRA012" not in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))

    def test_timing_layer_sanctioned(self):
        # SegmentTimer owns its operation span from __init__ to
        # done() -- the sanctioned non-lexical holder.
        src = ("from . import tracing\n"
               "class SegmentTimer:\n"
               "    def __init__(self, operation):\n"
               "        self._span = tracing.start_span(operation)\n")
        assert "TPUDRA012" not in rules_of(
            lint_source(src, rel="pkg/timing.py"))

    def test_tracing_layer_ctor_sanctioned(self):
        src = ("def start_span(name, ctx):\n"
               "    return Span(name, ctx)\n")
        assert "TPUDRA012" not in rules_of(
            lint_source(src, rel="pkg/tracing.py"))

    def test_recorder_record_clean(self):
        src = ("from . import flightrecorder\n"
               "def good(uid):\n"
               "    flightrecorder.default().record(uid, 'fit',\n"
               "                                    outcome='ok')\n")
        assert "TPUDRA012" not in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))


class TestTelemetryMutationRule:
    """TPUDRA013: telemetry ring / fleet-aggregator mutations
    (record_sample / fold_*) are fenced to pkg/fleetstate.py,
    pkg/anomaly.py and kubeletplugin/health.py -- everyone else feeds
    through the health-poll sampling seam or
    FleetAggregator.observe_pass."""

    def test_ring_mutation_outside_layer_flagged(self):
        src = ("from .fleetstate import default_ring\n"
               "def bad(sample):\n"
               "    default_ring().record_sample(sample)\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA013" in rules_of(findings)

    def test_fold_outside_layer_flagged(self):
        src = ("def bad(fleet, cands, nodes):\n"
               "    fleet.fold_node_telemetry(cands, nodes)\n")
        findings = lint_source(src, rel="kubeletplugin/driver.py")
        assert "TPUDRA013" in rules_of(findings)

    def test_health_poll_producer_sanctioned(self):
        src = ("def sample(self, samples):\n"
               "    for s in samples:\n"
               "        self.telemetry_ring.record_sample(s)\n")
        assert "TPUDRA013" not in rules_of(
            lint_source(src, rel="kubeletplugin/health.py"))

    def test_stray_same_named_file_not_sanctioned(self):
        # Rel-path suffix sanctioning (the TPUDRA011 lesson): a future
        # computedomain/daemon/health.py gets NO mutation rights just
        # for its basename.
        src = ("def bad(ring, s):\n"
               "    ring.record_sample(s)\n")
        findings = lint_source(src, rel="computedomain/daemon/health.py")
        assert "TPUDRA013" in rules_of(findings)

    def test_fleetstate_internal_fold_sanctioned(self):
        src = ("class FleetAggregator:\n"
               "    def observe_pass(self, snap):\n"
               "        self.fold_pass(snap)\n")
        assert "TPUDRA013" not in rules_of(
            lint_source(src, rel="pkg/fleetstate.py"))

    def test_observe_pass_entry_clean_everywhere(self):
        # The public fold entry is NOT a fenced mutation: the
        # scheduler calls it every full pass.
        src = ("def sync(self, snap, alloc):\n"
               "    self.fleet.observe_pass(snap, alloc, 0)\n")
        assert "TPUDRA013" not in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))


class TestPartitionSpecRule:
    """TPUDRA014: PartitionSet/PartitionProfile construction and
    partitionsets CRD writes are fenced to pkg/autoscale/ +
    pkg/partition/spec.py (rel-path sanctioned like TPUDRA011/013)."""

    def test_spec_construction_outside_flagged(self):
        src = ("from ..pkg.partition import PartitionSet\n"
               "def bad():\n"
               "    return PartitionSet(profiles=())\n")
        findings = lint_source(src, rel="kubeletplugin/driver.py")
        assert "TPUDRA014" in rules_of(findings)

    def test_profile_construction_outside_flagged(self):
        src = ("from ..partition.spec import PartitionProfile\n"
               "def bad():\n"
               "    return PartitionProfile(name='x', subslice='1x1')\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA014" in rules_of(findings)

    def test_attribute_form_flagged(self):
        src = ("from ..pkg.partition import spec\n"
               "def bad():\n"
               "    return spec.PartitionSet(profiles=())\n")
        findings = lint_source(src, rel="kubeletplugin/main.py")
        assert "TPUDRA014" in rules_of(findings)

    def test_parse_classmethods_stay_open(self):
        src = ("from ..pkg.partition import PartitionSet\n"
               "def good(path):\n"
               "    a = PartitionSet.from_file(path)\n"
               "    b = PartitionSet.from_dict({})\n"
               "    return a, b\n")
        assert "TPUDRA014" not in rules_of(
            lint_source(src, rel="kubeletplugin/main.py"))

    def test_autoscale_package_sanctioned(self):
        src = ("from ..partition.spec import PartitionProfile,"
               " PartitionSet\n"
               "def plan():\n"
               "    p = PartitionProfile(name='t-s8', subslice='1x1',\n"
               "                         max_tenants=8)\n"
               "    return PartitionSet(profiles=(p,))\n")
        assert "TPUDRA014" not in rules_of(
            lint_source(src, rel="pkg/autoscale/planner.py"))

    def test_spec_definition_site_sanctioned(self):
        src = ("def from_dict(cls, d):\n"
               "    return PartitionSet(profiles=())\n")
        assert "TPUDRA014" not in rules_of(
            lint_source(src, rel="pkg/partition/spec.py"))

    def test_stray_same_named_file_not_sanctioned(self):
        src = ("def bad():\n"
               "    return PartitionSet(profiles=())\n")
        findings = lint_source(src, rel="computedomain/plugin/spec.py")
        assert "TPUDRA014" in rules_of(findings)

    def test_crd_write_outside_flagged(self):
        src = ("def bad(kube, obj):\n"
               "    kube.create('resource.tpu.dra', 'v1beta1',\n"
               "                'partitionsets', obj)\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA014" in rules_of(findings)

    def test_crd_patch_outside_flagged(self):
        src = ("def bad(kube, name, patch):\n"
               "    kube.patch('resource.tpu.dra', 'v1beta1',\n"
               "               'partitionsets', name, patch)\n")
        findings = lint_source(src, rel="kubeletplugin/driver.py")
        assert "TPUDRA014" in rules_of(findings)

    def test_crd_write_in_controller_sanctioned(self):
        src = ("def apply(self, spec):\n"
               "    self.kube.patch('resource.tpu.dra', 'v1beta1',\n"
               "                    'partitionsets', self.crd_name,\n"
               "                    {'spec': spec})\n")
        assert "TPUDRA014" not in rules_of(
            lint_source(src, rel="pkg/autoscale/controller.py"))

    def test_crd_reads_stay_open(self):
        src = ("def watch(kube):\n"
               "    return kube.list('resource.tpu.dra', 'v1beta1',\n"
               "                     'partitionsets')\n")
        assert "TPUDRA014" not in rules_of(
            lint_source(src, rel="kubeletplugin/driver.py"))


class TestPowerPrewarmMutationRule:
    """TPUDRA015: AllocationState.power_debit/power_credit are fenced
    to pkg/schedcache.py and PartitionEngine.set_prewarm to the engine
    + the node driver's CRD-watch path (rel-path sanctioned like
    TPUDRA011/013/014)."""

    def test_power_debit_outside_flagged(self):
        src = ("def bad(alloc, node):\n"
               "    alloc.power_debit(node, 250)\n")
        findings = lint_source(src, rel="pkg/scheduler.py")
        assert "TPUDRA015" in rules_of(findings)

    def test_power_credit_outside_flagged(self):
        src = ("def bad(self, node):\n"
               "    self._alloc.power_credit(node, 250)\n")
        findings = lint_source(src, rel="pkg/recovery.py")
        assert "TPUDRA015" in rules_of(findings)

    def test_power_mutation_definition_site_sanctioned(self):
        src = ("class AllocationState:\n"
               "    def _apply_locked(self, cand):\n"
               "        self.power_debit(cand.node, cand.power_watts)\n")
        assert "TPUDRA015" not in rules_of(
            lint_source(src, rel="pkg/schedcache.py"))

    def test_stray_schedcache_not_sanctioned(self):
        src = ("def bad(alloc):\n"
               "    alloc.power_debit('n', 1)\n")
        findings = lint_source(src,
                               rel="computedomain/schedcache.py")
        assert "TPUDRA015" in rules_of(findings)

    def test_power_snapshot_read_stays_open(self):
        src = ("def good(alloc):\n"
               "    return alloc.power_snapshot()\n")
        assert "TPUDRA015" not in rules_of(
            lint_source(src, rel="pkg/scheduler.py"))

    def test_set_prewarm_outside_flagged(self):
        src = ("def bad(engine):\n"
               "    engine.set_prewarm({'web-s8': 4})\n")
        findings = lint_source(src, rel="pkg/autoscale/controller.py")
        assert "TPUDRA015" in rules_of(findings)

    def test_set_prewarm_driver_path_sanctioned(self):
        src = ("def apply_prewarm(self, hints):\n"
               "    return self.state.partition_engine.set_prewarm(\n"
               "        hints or {})\n")
        assert "TPUDRA015" not in rules_of(
            lint_source(src, rel="kubeletplugin/driver.py"))

    def test_set_prewarm_engine_sanctioned(self):
        src = ("class PartitionEngine:\n"
               "    def apply(self, ps):\n"
               "        self.set_prewarm({})\n")
        assert "TPUDRA015" not in rules_of(
            lint_source(src, rel="pkg/partition/engine.py"))

    def test_stray_engine_not_sanctioned(self):
        src = ("def bad(engine):\n"
               "    engine.set_prewarm({})\n")
        findings = lint_source(src, rel="computedomain/engine.py")
        assert "TPUDRA015" in rules_of(findings)


class TestWholePackageGate:
    """The tier-1 CI gate from ISSUE 3: zero non-baselined findings
    over the shipped package, with the committed baseline EMPTY (every
    real violation the linter surfaced was fixed, not suppressed)."""

    def test_package_is_clean(self):
        report = run_lint([PKG], baseline=BASELINE, root=REPO)
        assert report.files_scanned > 90
        active = report.active
        assert not active, "non-baselined findings:\n" + "\n".join(
            str(f) for f in active)

    def test_committed_baseline_is_empty(self):
        with open(BASELINE, encoding="utf-8") as f:
            doc = json.load(f)
        assert doc["suppressions"] == {}, (
            "the baseline exists for FUTURE pre-existing findings; "
            "everything current must be fixed at the source"
        )

    def test_make_target_contract(self):
        """`make lint-analysis` == the module CLI over the package with
        the committed baseline; pin the exit-0 contract."""
        proc = subprocess.run(
            [sys.executable, "-m", "k8s_dra_driver_gpu_tpu.pkg.analysis",
             "k8s_dra_driver_gpu_tpu", "--baseline", BASELINE],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "PYTHONPATH": REPO},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 non-baselined finding(s)" in proc.stdout


@pytest.mark.parametrize("rule", sorted(RULES))
def test_rule_catalog_documented(rule):
    """Every rule ID must be described in docs/analysis.md."""
    doc = open(os.path.join(REPO, "docs", "analysis.md"),
               encoding="utf-8").read()
    assert rule in doc, f"{rule} missing from docs/analysis.md"
