"""ICI topology-aware placement engine tier: grid parsing (wraparound,
partial grids, missing coordinates), sub-torus shape enumeration,
scorer ranking determinism, host-adjacency ranking, simulator
determinism + metrics export -- and the scheduler-level proof that a
4-chip claim lands on a contiguous 2x2 sub-torus instead of a
scattered set (plus the first-fit fallback when the gate is off)."""

import random

import pytest
from prometheus_client import generate_latest

from k8s_dra_driver_gpu_tpu.computedomain import (
    API_GROUP,
    API_VERSION,
    PREFERRED_NODES_ANNOTATION,
)
from k8s_dra_driver_gpu_tpu.computedomain.controller.controller import (
    ComputeDomainController,
)
from k8s_dra_driver_gpu_tpu.pkg.featuregates import FeatureGates
from k8s_dra_driver_gpu_tpu.pkg.kubeclient import FakeKubeClient
from k8s_dra_driver_gpu_tpu.pkg.metrics import PlacementMetrics
from k8s_dra_driver_gpu_tpu.pkg.scheduler import DraScheduler
from k8s_dra_driver_gpu_tpu.pkg.topology import (
    TorusGrid,
    default_wrap,
    enumerate_shapes,
    fragmentation_score,
    largest_free_shape,
    order_candidates,
    placements,
    rank_adjacent_hosts,
    rank_placements,
    shapes_for_count,
)
from k8s_dra_driver_gpu_tpu.pkg.topology.sim import (
    grid_for_type,
    make_trace,
    run_placement_bench,
    simulate_churn,
)

RES = ("resource.k8s.io", "v1")


def chip_device(name, x=None, y=None, z=None, topology="4x4",
                platform="v5e", **extra):
    attrs = {
        "platform": {"string": platform},
        "topology": {"string": topology},
    }
    if x is not None:
        attrs["iciX"] = {"int": x}
    if y is not None:
        attrs["iciY"] = {"int": y}
    if z is not None:
        attrs["iciZ"] = {"int": z}
    for k, v in extra.items():
        attrs[k] = v
    return {"name": name, "attributes": attrs, "capacity": {}}


def grid_4x4(names=None):
    devs = []
    i = 0
    for y in range(4):
        for x in range(4):
            name = names[i] if names else f"chip-{i}"
            devs.append(chip_device(name, x, y))
            i += 1
    return TorusGrid.from_devices(devs)


class TestGridParsing:
    def test_parses_typed_attributes_and_dims(self):
        g = grid_4x4()
        assert g.dims == (4, 4, 1)
        assert g.coords["chip-0"] == (0, 0, 0)
        assert g.coords["chip-5"] == (1, 1, 0)
        assert g.uncoordinated == ()
        assert g.wrap == (False, False, False)  # v5e 4x4: mesh, no rings

    def test_v5p_axes_of_four_wrap(self):
        devs = [chip_device(f"c{i}", i % 2, (i // 2) % 2, i // 4,
                            topology="2x2x4", platform="v5p")
                for i in range(16)]
        g = TorusGrid.from_devices(devs)
        assert g.dims == (2, 2, 4)
        assert g.wrap == (False, False, True)
        # Ring distance across the z seam: 0 -> 3 is one hop.
        assert g.hop_distance((0, 0, 0), (0, 0, 3)) == 1

    def test_missing_coordinates_are_quarantined(self):
        devs = [chip_device("good", 0, 0),
                chip_device("no-coords"),  # e.g. a sub-slice device
                chip_device("half", x=1)]  # iciY missing
        g = TorusGrid.from_devices(devs)
        assert set(g.coords) == {"good"}
        assert set(g.uncoordinated) == {"no-coords", "half"}

    def test_duplicate_and_out_of_grid_coords_demoted(self):
        devs = [chip_device("a", 0, 0), chip_device("b", 0, 0),
                chip_device("oob", 9, 9)]
        g = TorusGrid.from_devices(devs)
        assert set(g.coords) == {"a"}
        assert set(g.uncoordinated) == {"b", "oob"}

    def test_partial_grid_keeps_full_slice_dims(self):
        # One host of a 4x4 slice: only a 2x2 corner visible, global
        # coordinates, dims still the declared full slice.
        devs = [chip_device(f"c{i}", 2 + i % 2, 2 + i // 2)
                for i in range(4)]
        g = TorusGrid.from_devices(devs)
        assert g.dims == (4, 4, 1)
        assert g.coords["c3"] == (3, 3, 0)

    def test_dims_fall_back_to_bounding_box(self):
        devs = [{"name": "a", "attributes": {"iciX": {"int": 1},
                                             "iciY": {"int": 2}}}]
        g = TorusGrid.from_devices(devs)
        assert g.dims == (2, 3, 1)

    def test_default_wrap_policy(self):
        assert default_wrap("v5p", (4, 4, 4)) == (True, True, True)
        assert default_wrap("v5p", (2, 2, 4)) == (False, False, True)
        assert default_wrap("v5e", (4, 4, 1)) == (False, False, False)
        assert default_wrap("v5e", (16, 16, 1)) == (True, True, False)
        assert default_wrap("", (8, 8, 8)) == (False, False, False)


class TestShapes:
    def test_shapes_for_count_prefers_cubic(self):
        g = grid_4x4()
        assert shapes_for_count(g, 4)[0] == (2, 2, 1)
        assert (4, 1, 1) in shapes_for_count(g, 4)
        assert shapes_for_count(g, 16)[0] == (4, 4, 1)
        assert shapes_for_count(g, 3) == [(1, 3, 1), (3, 1, 1)]
        assert shapes_for_count(g, 32) == []  # bigger than the slice

    def test_enumerate_shapes_largest_first(self):
        g = grid_4x4()
        shapes = enumerate_shapes(g)
        assert shapes[0] == (4, 4, 1)
        vols = [w * h * d for w, h, d in shapes]
        assert vols == sorted(vols, reverse=True)

    def test_placement_counts_no_wrap(self):
        g = grid_4x4()
        assert len(placements(g, (2, 2, 1))) == 9
        assert len(placements(g, (4, 1, 1))) == 4
        assert len(placements(g, (4, 4, 1))) == 1

    def test_wraparound_placements_cross_the_seam(self):
        devs = [chip_device(f"c{i}", i % 2, (i // 2) % 2, i // 4,
                            topology="2x2x4", platform="v5p")
                for i in range(16)]
        g = TorusGrid.from_devices(devs)
        # A 2-deep block: the wrapping z ring contributes 4 anchors per
        # (x, y) column (incl. the seam-crossing z=3 one), not 3.
        zs = placements(g, (1, 1, 2))
        assert len(zs) == 2 * 2 * 4
        assert ((0, 0, 3), (0, 0, 0)) in zs
        # The non-wrapping x axis: 1 anchor only.
        assert len(placements(g, (2, 1, 1))) == 1 * 2 * 4


class TestScorer:
    def test_four_chips_pick_a_quad_on_an_empty_grid(self):
        g = grid_4x4()
        best = rank_placements(g, list(g.coords), 4)[0]
        cells = {g.coords[n] for n in best}
        xs = {c[0] for c in cells}
        ys = {c[1] for c in cells}
        assert len(xs) == 2 and len(ys) == 2, f"not a 2x2: {cells}"
        assert g.max_hops(cells) == 2

    def test_ranking_is_deterministic_under_input_shuffle(self):
        g = grid_4x4()
        names = list(g.coords)
        baseline = rank_placements(g, names, 4)
        for seed in range(3):
            shuffled = names[:]
            random.Random(seed).shuffle(shuffled)
            assert rank_placements(g, shuffled, 4) == baseline
        assert order_candidates(g, names, 4) == \
            order_candidates(g, names, 4)

    def test_fragmented_grid_finds_the_surviving_quad(self):
        g = grid_4x4()
        # Take the whole grid except a 2x2 at (2..3, 2..3) plus two
        # scattered singles; the only contiguous quad must win.
        keep = {(2, 2, 0), (3, 2, 0), (2, 3, 0), (3, 3, 0),
                (0, 0, 0), (0, 2, 0)}
        free = [n for n, c in g.coords.items() if c in keep]
        best = rank_placements(g, free, 4)[0]
        assert {g.coords[n] for n in best} == \
            {(2, 2, 0), (3, 2, 0), (2, 3, 0), (3, 3, 0)}

    def test_greedy_fallback_when_no_exact_subtorus(self):
        g = grid_4x4()
        # An L of 3 cells: count=3 needs a 1x3 line, none is free ->
        # the greedy fallback must still return the (compact) L.
        keep = {(0, 0, 0), (1, 0, 0), (0, 1, 0)}
        free = [n for n, c in g.coords.items() if c in keep]
        ranked = rank_placements(g, free, 3)
        assert ranked, "fallback produced nothing"
        assert {g.coords[n] for n in ranked[0]} == keep

    def test_order_candidates_keeps_every_name(self):
        g = grid_4x4()
        names = list(g.coords)
        ordered = order_candidates(g, names, 4)
        assert sorted(ordered) == sorted(names)
        # Uncoordinated-only input: no signal, caller keeps first-fit.
        g2 = TorusGrid.from_devices([chip_device("u1"),
                                     chip_device("u2")])
        assert order_candidates(g2, ["u1", "u2"], 2) is None

    def test_fragmentation_score_and_largest_shape(self):
        g = grid_4x4()
        whole = set(g.coords.values())
        assert fragmentation_score(g, whole) == 0.0
        assert largest_free_shape(g, whole) == ((4, 4, 1), 16)
        assert fragmentation_score(g, set()) == 0.0
        # A diagonal: 4 free chips, nothing bigger than a single fits.
        diag = {(i, i, 0) for i in range(4)}
        assert largest_free_shape(g, diag)[1] == 1
        assert fragmentation_score(g, diag) == pytest.approx(0.75)

    def test_largest_free_shape_memoized(self, monkeypatch):
        """The (grid signature, free set) memo: the second identical
        query must not re-enumerate shapes -- the FleetAggregator fold
        and the defrag what-if loop both lean on this."""
        from k8s_dra_driver_gpu_tpu.pkg.topology import score

        score.clear_shape_memo()
        g = grid_4x4()
        free = {(i, i, 0) for i in range(4)}
        cold = largest_free_shape(g, free)
        calls = []
        real = score.enumerate_shapes
        monkeypatch.setattr(
            score, "enumerate_shapes",
            lambda *a, **kw: calls.append(1) or real(*a, **kw))
        assert largest_free_shape(g, free) == cold
        assert calls == [], "memo miss on an identical query"
        # An EQUIVALENT grid built separately shares the row (the
        # signature is geometry, not object identity)...
        g2 = grid_4x4()
        assert largest_free_shape(g2, free) == cold
        assert calls == []
        # ...and a different free set is a genuine miss.
        largest_free_shape(g, set(g.coords.values()))
        assert calls
        score.clear_shape_memo()

    def test_memo_never_changes_results(self):
        """Property check: memoized answers byte-match a cold sweep
        across a seeded set of free subsets."""
        from k8s_dra_driver_gpu_tpu.pkg.topology import score

        g = grid_4x4()
        cells = sorted(g.coords.values())
        rng = random.Random(20260804)
        subsets = [set(rng.sample(cells, rng.randint(0, len(cells))))
                   for _ in range(12)]
        score.clear_shape_memo()
        cold = [largest_free_shape(g, s) for s in subsets]
        warm = [largest_free_shape(g, s) for s in subsets]
        assert warm == cold
        score.clear_shape_memo()
        assert [largest_free_shape(g, s) for s in subsets] == cold


class TestHostRanking:
    def test_best_window_of_consecutive_workers_first(self):
        hosts = {"node-a": 0, "node-b": 2, "node-c": 1, "node-d": 5}
        assert rank_adjacent_hosts(hosts, 2) == \
            ["node-a", "node-c", "node-b", "node-d"]
        # Gang of 3: workers 0,1,2 -> a,c,b; d trails.
        assert rank_adjacent_hosts(hosts, 3) == \
            ["node-a", "node-c", "node-b", "node-d"]

    def test_window_skips_a_gap(self):
        hosts = {"h0": 0, "h4": 4, "h5": 5}
        assert rank_adjacent_hosts(hosts, 2) == ["h4", "h5", "h0"]

    def test_degenerate_sizes(self):
        hosts = {"b": 1, "a": 0}
        assert rank_adjacent_hosts(hosts, 1) == ["a", "b"]
        assert rank_adjacent_hosts(hosts, 9) == ["a", "b"]
        assert rank_adjacent_hosts({}, 2) == []


class TestSimulator:
    def test_same_seed_same_results(self):
        g = grid_for_type("v5e-16")
        trace = make_trace(60, seed=3)
        a = simulate_churn(g, trace, policy="scored")
        b = simulate_churn(g, trace, policy="scored")
        assert a == b

    def test_scored_beats_first_fit_on_the_default_trace(self):
        res = run_placement_bench(steps=120)
        for topo, policies in res.items():
            assert policies["scored"]["frag_mean"] <= \
                policies["first_fit"]["frag_mean"], topo
            assert policies["scored"]["compactness_mean_hops"] <= \
                policies["first_fit"]["compactness_mean_hops"], topo

    def test_metrics_families_are_exported(self):
        m = PlacementMetrics()
        g = grid_for_type("v5e-16")
        simulate_churn(g, make_trace(40, seed=1), policy="scored",
                       metrics=m, pool="test-pool")
        text = generate_latest(m.registry).decode()
        assert 'tpu_dra_placement_frag_score{pool="test-pool"}' in text
        assert 'tpu_dra_placement_largest_free_shape_chips' in text
        assert 'tpu_dra_placement_compactness_bucket' in text


# -- scheduler-level: topology-scored device picking --------------------------


def publish_grid_slice(kube, node="node-a", pool="node-a", count=16,
                       side=4):
    devices = []
    for i in range(count):
        devices.append(chip_device(f"chip-{i}", i % side, i // side,
                                   topology=f"{side}x{side}"))
    kube.create(*RES, "resourceslices", {
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceSlice",
        "metadata": {"name": f"grid-{node}"},
        "spec": {
            "driver": "tpu.dra.dev", "nodeName": node,
            "pool": {"name": pool, "generation": 1,
                     "resourceSliceCount": 1},
            "devices": devices,
        },
    })


def block_devices(kube, devices, name="blocker", node="node-a",
                  pool="node-a"):
    """A pre-existing allocation pinning specific chips (fragmenter)."""
    kube.create(*RES, "resourceclaims", {
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"devices": {"requests": [
            {"name": "tpu",
             "exactly": {"deviceClassName": "tpu.dra.dev"}}]}},
        "status": {"allocation": {"devices": {"results": [
            {"request": "tpu", "driver": "tpu.dra.dev", "pool": pool,
             "device": d} for d in devices
        ]}}},
    }, namespace="default")


@pytest.fixture()
def kube():
    import os

    from k8s_dra_driver_gpu_tpu.pkg.chartrender import (
        manifests,
        render_chart,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    chart = os.path.join(repo, "deployments", "helm", "tpu-dra-driver")
    k = FakeKubeClient()
    for doc in manifests(render_chart(chart)):
        if doc.get("kind") == "DeviceClass":
            k.create(*RES, "deviceclasses", doc)
    return k


def four_chip_claim(kube, name="quad", count=4):
    kube.create(*RES, "resourceclaims", {
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"devices": {"requests": [
            {"name": "tpu", "exactly": {
                "deviceClassName": "tpu.dra.dev", "count": count}}]}},
    }, namespace="default")


def allocated_devices(kube, name):
    claim = kube.get(*RES, "resourceclaims", name, "default")
    alloc = claim.get("status", {}).get("allocation")
    assert alloc, f"claim {name} not allocated"
    return [r["device"] for r in alloc["devices"]["results"]]


class TestSchedulerTopologyPlacement:
    def coords_of(self, devices, side=4):
        out = set()
        for d in devices:
            i = int(d.split("-")[1])
            out.add((i % side, i // side))
        return out

    def test_quad_lands_on_contiguous_2x2(self, kube):
        """Gate on: a 4-chip claim on a fragmented 4x4 v5e grid must
        allocate the ICI-contiguous 2x2 sub-torus, not the scattered
        first-fit set."""
        publish_grid_slice(kube)
        # Fragment: pin everything except a 2x2 at (1..2, 1..2) and
        # four scattered chips that name-sort FIRST (first-fit bait).
        free = {(1, 1), (2, 1), (1, 2), (2, 2),
                (0, 0), (3, 0), (0, 3), (3, 3)}
        blocked = [f"chip-{y * 4 + x}" for y in range(4)
                   for x in range(4) if (x, y) not in free]
        block_devices(kube, blocked)
        four_chip_claim(kube)
        DraScheduler(kube, gates=FeatureGates()).sync_once()
        got = self.coords_of(allocated_devices(kube, "quad"))
        assert got == {(1, 1), (2, 1), (1, 2), (2, 2)}, got

    def test_gate_off_falls_back_to_first_fit(self, kube):
        publish_grid_slice(kube)
        free = {(1, 1), (2, 1), (1, 2), (2, 2),
                (0, 0), (3, 0), (0, 3), (3, 3)}
        blocked = [f"chip-{y * 4 + x}" for y in range(4)
                   for x in range(4) if (x, y) not in free]
        block_devices(kube, blocked)
        four_chip_claim(kube)
        gates = FeatureGates({"TopologyAwarePlacement": False})
        DraScheduler(kube, gates=gates).sync_once()
        got = self.coords_of(allocated_devices(kube, "quad"))
        # First-fit takes the four first free devices in publication
        # order -- a scattered set, NOT the quad.
        assert got != {(1, 1), (2, 1), (1, 2), (2, 2)}, \
            "gate off still picked the scored placement"

    def test_empty_grid_quad_is_compact(self, kube):
        publish_grid_slice(kube)
        four_chip_claim(kube)
        DraScheduler(kube, gates=FeatureGates()).sync_once()
        cells = self.coords_of(allocated_devices(kube, "quad"))
        xs = {c[0] for c in cells}
        ys = {c[1] for c in cells}
        assert len(xs) == 2 and len(ys) == 2, f"not a 2x2: {cells}"

    def test_match_attribute_still_enforced_with_scoring(self, kube):
        """matchAttribute pins, the scorer chooses: constraining iciY
        on 2 chips must still land one row, topology gate on."""
        publish_grid_slice(kube)
        kube.create(*RES, "resourceclaims", {
            "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
            "metadata": {"name": "row", "namespace": "default"},
            "spec": {"devices": {
                "requests": [{"name": "tpu", "exactly": {
                    "deviceClassName": "tpu.dra.dev", "count": 2}}],
                "constraints": [{"matchAttribute": "tpu.dra.dev/iciY"}],
            }},
        }, namespace="default")
        DraScheduler(kube, gates=FeatureGates()).sync_once()
        cells = self.coords_of(allocated_devices(kube, "row"))
        assert len({y for _, y in cells}) == 1, cells
        # And adjacent, because the scorer ranked the pair.
        xs = sorted(x for x, _ in cells)
        assert xs[1] - xs[0] == 1, cells

    def test_placement_metrics_observed(self, kube):
        publish_grid_slice(kube)
        four_chip_claim(kube)
        metrics = PlacementMetrics()
        DraScheduler(kube, gates=FeatureGates(),
                     metrics=metrics).sync_once()
        text = generate_latest(metrics.registry).decode()
        assert 'tpu_dra_placement_frag_score' in text
        assert 'tpu_dra_placement_compactness_bucket' in text


# -- ComputeDomain: ICI-adjacent host preference ------------------------------


def publish_channel_slice(kube, node):
    kube.create(*RES, "resourceslices", {
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceSlice",
        "metadata": {"name": f"cd-{node}"},
        "spec": {
            "driver": "compute-domain.tpu.dra.dev", "nodeName": node,
            "pool": {"name": f"cd-{node}", "generation": 1,
                     "resourceSliceCount": 1},
            "devices": [
                {"name": f"channel-{i}",
                 "attributes": {"type": {"string": "channel"},
                                "channel": {"int": i},
                                "cliqueId": {"string": "0"}},
                 "capacity": {}}
                for i in range(4)
            ],
        },
    })


def make_cd(kube, name="cd1", num_nodes=2, annotations=None):
    return kube.create(API_GROUP, API_VERSION, "computedomains", {
        "apiVersion": f"{API_GROUP}/{API_VERSION}",
        "kind": "ComputeDomain",
        "metadata": {"name": name, "namespace": "default",
                     **({"annotations": annotations} if annotations
                        else {})},
        "spec": {"numNodes": num_nodes,
                 "channel": {"resourceClaimTemplate":
                             {"name": f"{name}-channel"}}},
    }, namespace="default")


def channel_claim(kube, name, cd_uid):
    kube.create(*RES, "resourceclaims", {
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"devices": {
            "requests": [{"name": "channel", "exactly": {
                "deviceClassName":
                    "compute-domain-default-channel.tpu.dra.dev"}}],
            "config": [{"requests": ["channel"], "opaque": {
                "driver": "compute-domain.tpu.dra.dev",
                "parameters": {
                    "apiVersion": f"{API_GROUP}/{API_VERSION}",
                    "kind": "ComputeDomainChannelConfig",
                    "domainID": cd_uid,
                },
            }}],
        }},
    }, namespace="default")


class TestGangNodePreference:
    def test_controller_stamps_adjacent_window(self, kube):
        # workerIds: node-a=0, node-b=2, node-c=1, node-d=5. Gang of 2
        # -> the tight window is workers 0,1 = node-a,node-c.
        for node, wid in (("node-a", 0), ("node-b", 2),
                          ("node-c", 1), ("node-d", 5)):
            kube.create(*RES, "resourceslices", {
                "apiVersion": "resource.k8s.io/v1",
                "kind": "ResourceSlice",
                "metadata": {"name": f"chips-{node}"},
                "spec": {
                    "driver": "tpu.dra.dev", "nodeName": node,
                    "pool": {"name": node, "generation": 1,
                             "resourceSliceCount": 1},
                    "devices": [chip_device(
                        "chip-0", 0, 0,
                        workerId={"int": wid})],
                },
            })
        cd = make_cd(kube, num_nodes=2)
        controller = ComputeDomainController(kube)
        try:
            controller.reconcile(
                kube.get(API_GROUP, API_VERSION, "computedomains",
                         "cd1", "default"))
        finally:
            controller.queue.shutdown(wait=False)
        got = kube.get(API_GROUP, API_VERSION, "computedomains", "cd1",
                       "default")
        ann = got["metadata"]["annotations"][PREFERRED_NODES_ANNOTATION]
        assert ann == "node-a,node-c", ann
        assert cd["metadata"]["uid"]  # uid existed for the scheduler

    def test_duplicate_worker_ids_stamp_no_window(self, kube):
        """workerIds are slice-local; duplicates mean several ICI
        fabrics are visible and a worker-order window would interleave
        them -- the controller must stamp nothing."""
        for node, wid in (("node-a", 0), ("node-b", 1),
                          ("node-c", 0), ("node-d", 1)):
            kube.create(*RES, "resourceslices", {
                "apiVersion": "resource.k8s.io/v1",
                "kind": "ResourceSlice",
                "metadata": {"name": f"chips-{node}"},
                "spec": {
                    "driver": "tpu.dra.dev", "nodeName": node,
                    "pool": {"name": node, "generation": 1,
                             "resourceSliceCount": 1},
                    "devices": [chip_device(
                        "chip-0", 0, 0, workerId={"int": wid})],
                },
            })
        make_cd(kube, num_nodes=2)
        controller = ComputeDomainController(kube)
        try:
            controller.reconcile(
                kube.get(API_GROUP, API_VERSION, "computedomains",
                         "cd1", "default"))
        finally:
            controller.queue.shutdown(wait=False)
        got = kube.get(API_GROUP, API_VERSION, "computedomains", "cd1",
                       "default")
        assert PREFERRED_NODES_ANNOTATION not in (
            got["metadata"].get("annotations") or {})

    def test_scheduler_prefers_the_window(self, kube):
        for node in ("node-a", "node-b", "node-c"):
            publish_channel_slice(kube, node)
        cd = make_cd(kube, annotations={
            PREFERRED_NODES_ANNOTATION: "node-b,node-c"})
        channel_claim(kube, "gang-0", cd["metadata"]["uid"])
        channel_claim(kube, "gang-1", cd["metadata"]["uid"])
        DraScheduler(kube, gates=FeatureGates()).sync_once()
        nodes = set()
        for name in ("gang-0", "gang-1"):
            claim = kube.get(*RES, "resourceclaims", name, "default")
            alloc = claim["status"]["allocation"]
            for term in alloc["nodeSelector"]["nodeSelectorTerms"]:
                for mf in term["matchFields"]:
                    nodes.add(mf["values"][0])
        # Both members in the ICI-adjacent window, spread over it --
        # node-a (name-sorts first, equally empty) must lose.
        assert nodes == {"node-b", "node-c"}, nodes

    def test_gate_off_ignores_the_window(self, kube):
        for node in ("node-a", "node-b"):
            publish_channel_slice(kube, node)
        cd = make_cd(kube, annotations={
            PREFERRED_NODES_ANNOTATION: "node-b"})
        channel_claim(kube, "solo", cd["metadata"]["uid"])
        gates = FeatureGates({"TopologyAwarePlacement": False})
        DraScheduler(kube, gates=gates).sync_once()
        claim = kube.get(*RES, "resourceclaims", "solo", "default")
        term = claim["status"]["allocation"]["nodeSelector"][
            "nodeSelectorTerms"][0]
        assert term["matchFields"][0]["values"] == ["node-a"]
