"""Tier-1 power-sched smoke: the `make bench-powersched-smoke`
contract as a non-slow test. Runs bench.py --powersched at reduced
scale and asserts the telemetry->placement acceptance bar: pre-warming
cuts burst attach p99 >= 3x vs the cold lazy-create path with every
warm attach a counted pre-warm hit, and the power-capped-rack chaos
run sheds load with zero claim-e2e SLO breaches, zero pending, zero
per-node power over-commit recomputed from the final allocations,
last-resort-only use of the anomaly-tainted chip, and converged
steady-state passes at zero kube writes -- plus the
BENCH_powersched.json trajectory file actually written."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Keep in sync with the Makefile bench-powersched-smoke target.
SMOKE_ENV = {
    "BENCH_POWERSCHED_NODES": "4",
    "BENCH_POWERSCHED_ROUNDS": "2",
    "BENCH_POWERSCHED_MIN_PREWARM_RATIO": "3.0",
}


def test_bench_powersched_smoke_closes_the_loop(tmp_path):
    out_json = tmp_path / "BENCH_powersched.json"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--powersched"],
        env={**os.environ, "PYTHONPATH": REPO, **SMOKE_ENV,
             "BENCH_POWERSCHED_OUT": str(out_json)},
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["metric"] == "powersched_prewarm_speedup"
    extras = doc["extras"]

    # THE latency bar: warm attaches >= 3x faster at p99 than cold
    # lazy creates, and every one of them hit a pre-warmed carve-out.
    assert doc["value"] >= 3.0
    assert extras["powersched_warm_attach_p99_ms"] is not None
    assert extras["powersched_prewarm_hits"] == \
        extras["powersched_prewarm_expected_hits"] > 0
    assert extras["powersched_cold_hits"] == 0

    # The power-capped rack sheds load instead of breaching:
    # everything allocated, inside the SLO, and the recomputed
    # per-node power audit stays under every cap.
    assert extras["powersched_pending"] == 0
    assert extras["powersched_slo_breaches"] == 0
    assert extras["powersched_power_overcommit"] == 0
    for node, used in extras["powersched_capped_rack_used_w"].items():
        assert used <= extras["powersched_rack_cap_w"], node

    # Anomaly avoidance is preference, not exclusion; steady state
    # stays write-free.
    assert extras["powersched_tainted_chip_avoid_ok"] == 1
    assert extras["powersched_steady_writes"] == 0

    recorded = json.loads(out_json.read_text())
    assert recorded["metric"] == "powersched_prewarm_speedup"
