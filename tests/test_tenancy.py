"""MultiTenancy enforcement tests: the per-claim agent admits tenants
against max-client and HBM budgets; the CDI preflight hook fails (exit
nonzero -> container start refused) for an over-budget tenant; grants
survive agent restarts; prepared claims re-own agents on plugin restart.

Reference role: cmd/gpu-kubelet-plugin/sharing.go:214-379 (MPS control
daemon Deployment + AssertReady + workload redirection).
"""

import json
import os

import pytest

from k8s_dra_driver_gpu_tpu.kubeletplugin.device_state import (
    Config,
    DeviceState,
)
from k8s_dra_driver_gpu_tpu.kubeletplugin.sharing import MultiTenancyManager
from k8s_dra_driver_gpu_tpu.kubeletplugin.tenancy_agent import (
    TenancyState,
    _handle_line,
)
from k8s_dra_driver_gpu_tpu.kubeletplugin.tenancy_preflight import (
    main as preflight_main,
)
from tests.fake_kube import make_claim, opaque

GI = 1 << 30
PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "k8s_dra_driver_gpu_tpu", "kubeletplugin")


def write_manifest(d, max_clients=2, capacity=4 * GI):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "tenancy.json"), "w") as f:
        json.dump({
            "chips": [0],
            "maxClients": max_clients,
            "hbmCapacityBytes": capacity,
            "hbmLimits": {"chip-0": 2 * GI},
        }, f)


class TestAdmissionLogic:
    def test_admits_within_budget(self, tmp_path):
        write_manifest(tmp_path)
        st = TenancyState(str(tmp_path))
        assert _handle_line(st, "REGISTER a 1073741824").startswith("OK")
        assert _handle_line(st, "REGISTER b 1073741824").startswith("OK")

    def test_denies_over_max_clients(self, tmp_path):
        write_manifest(tmp_path, max_clients=1)
        st = TenancyState(str(tmp_path))
        assert _handle_line(st, "REGISTER a 1").startswith("OK")
        out = _handle_line(st, "REGISTER b 1")
        assert out.startswith("DENIED") and "max clients" in out

    def test_denies_over_hbm_capacity(self, tmp_path):
        write_manifest(tmp_path, capacity=3 * GI)
        st = TenancyState(str(tmp_path))
        assert _handle_line(st, f"REGISTER a {2 * GI}").startswith("OK")
        out = _handle_line(st, f"REGISTER b {2 * GI}")
        assert out.startswith("DENIED") and "HBM budget" in out

    def test_release_frees_budget(self, tmp_path):
        write_manifest(tmp_path, capacity=2 * GI, max_clients=None)
        st = TenancyState(str(tmp_path))
        assert _handle_line(st, f"REGISTER a {2 * GI}").startswith("OK")
        assert _handle_line(st, f"REGISTER b {GI}").startswith("DENIED")
        assert _handle_line(st, "RELEASE a") == "OK released"
        assert _handle_line(st, f"REGISTER b {GI}").startswith("OK")

    def test_reregister_same_client_is_update_not_double_count(self, tmp_path):
        write_manifest(tmp_path, capacity=2 * GI, max_clients=1)
        st = TenancyState(str(tmp_path))
        assert _handle_line(st, f"REGISTER a {GI}").startswith("OK")
        assert _handle_line(st, f"REGISTER a {2 * GI}").startswith("OK")

    def test_grants_survive_agent_restart(self, tmp_path):
        write_manifest(tmp_path, max_clients=1)
        st = TenancyState(str(tmp_path))
        assert _handle_line(st, "REGISTER a 1").startswith("OK")
        st2 = TenancyState(str(tmp_path))  # fresh agent, same dir
        assert _handle_line(st2, "REGISTER b 1").startswith("DENIED")

    def test_tombstone_reclaims_lost_release(self, tmp_path):
        # A poststop that couldn't reach the agent leaves released.d/<id>;
        # the agent applies it before the next admission, so the dead
        # container's slot is reclaimed instead of leaking forever.
        write_manifest(tmp_path, max_clients=1)
        st = TenancyState(str(tmp_path))
        assert _handle_line(st, "REGISTER dead 1").startswith("OK")
        rd = tmp_path / "released.d"
        rd.mkdir()
        (rd / "dead").touch()
        assert _handle_line(st, "REGISTER alive 1").startswith("OK")
        assert not (rd / "dead").exists()

    def test_preflight_writes_tombstone_when_agent_unreachable(
        self, tmp_path
    ):
        assert preflight_main(["--dir", str(tmp_path), "--release",
                               "--client-id", "ctr-x"]) == 0
        assert (tmp_path / "released.d" / "ctr-x").exists()

    def test_preflight_skips_tombstone_when_dir_gone(self, tmp_path):
        # poststop racing Unprepare: the tenancy dir (and its sock
        # symlink) are already removed. The hook must NOT makedirs the
        # path back into existence -- a real dir would dodge the
        # dangling-symlink sweep in reconcile() and leak.
        gone = tmp_path / "sock" / "deadbeef1234"
        assert preflight_main(["--dir", str(gone), "--release",
                               "--client-id", "ctr-x"]) == 0
        assert not gone.exists()
        assert not (tmp_path / "sock").exists()

    def test_register_rejects_path_traversal_ids(self, tmp_path):
        write_manifest(tmp_path)
        st = TenancyState(str(tmp_path))
        assert _handle_line(st, "REGISTER ../evil 1").startswith("ERROR")

    def test_status_and_members(self, tmp_path):
        write_manifest(tmp_path)
        st = TenancyState(str(tmp_path))
        assert _handle_line(st, "STATUS") == "READY"
        _handle_line(st, "REGISTER a 5")
        doc = json.loads(_handle_line(st, "MEMBERS"))
        assert doc["clients"] == {"a": 5}


class TestEndToEndEnforcement:
    """Real agent process + real preflight, through DeviceState.prepare."""

    @pytest.fixture()
    def state(self, tmp_path):
        s = DeviceState(Config.mock(root=str(tmp_path / "root"),
                                    tenancy_agents=True))
        yield s
        s.stop()

    def _prepare_tenancy_claim(self, state, uid="c1", max_clients=2,
                               hbm_limit="8Gi"):
        cfgs = [{
            "parameters": opaque("TpuConfig", sharing={
                "strategy": "MultiTenancy",
                "multiTenancy": {
                    "maxClients": max_clients,
                    "hbmLimit": hbm_limit,
                },
            }),
        }]
        state.prepare(make_claim(uid, ["chip-0"], configs=cfgs))

    def test_prepare_spawns_ready_agent_and_injects_hook(self, state):
        from k8s_dra_driver_gpu_tpu.kubeletplugin.tenancy_agent import query

        self._prepare_tenancy_claim(state)
        d = state._tenancy._dir("c1", "tpu")
        assert query(d, "STATUS") == "READY"
        spec = state._cdi.read_spec("c1")
        hooks = spec["containerEdits"].get("hooks", [])
        assert hooks and hooks[0]["hookName"] == "createContainer"
        assert "--dir" in hooks[0]["args"]

    def test_second_over_budget_tenant_rejected(self, state, capsys):
        # v5e chip: 16 GiB HBM. Two tenants at 8Gi fit; a third tenant
        # (or one asking beyond the remainder) must be DENIED and the
        # preflight hook must exit nonzero = container start refused.
        self._prepare_tenancy_claim(state, hbm_limit="8Gi")
        d = state._tenancy._dir("c1", "tpu")
        assert preflight_main(["--dir", d, "--hbm-bytes",
                               str(8 * GI), "--client-id", "pod-a"]) == 0
        assert preflight_main(["--dir", d, "--hbm-bytes",
                               str(8 * GI), "--client-id", "pod-b"]) == 0
        rc = preflight_main(["--dir", d, "--hbm-bytes",
                             str(8 * GI), "--client-id", "pod-c"])
        assert rc == 1
        assert "DENIED" in capsys.readouterr().err

    def test_poststop_release_frees_restarted_containers_slot(self, state):
        # kubelet restarts a crashed container under a NEW OCI id; the
        # poststop hook must free the old id or the pod wedges on the
        # max-client check forever.
        self._prepare_tenancy_claim(state, max_clients=1, hbm_limit="8Gi")
        d = state._tenancy._dir("c1", "tpu")
        assert preflight_main(["--dir", d, "--hbm-bytes", "1",
                               "--client-id", "ctr-old"]) == 0
        assert preflight_main(["--dir", d, "--hbm-bytes", "1",
                               "--client-id", "ctr-new"]) == 1
        assert preflight_main(["--dir", d, "--release",
                               "--client-id", "ctr-old"]) == 0
        assert preflight_main(["--dir", d, "--hbm-bytes", "1",
                               "--client-id", "ctr-new"]) == 0

    def test_cdi_spec_carries_create_and_poststop_hooks(self, state):
        self._prepare_tenancy_claim(state)
        spec = state._cdi.read_spec("c1")
        hooks = {h["hookName"]: h for h in
                 spec["containerEdits"].get("hooks", [])}
        assert set(hooks) == {"createContainer", "poststop"}
        # OCI hook args include argv[0] == path.
        for h in hooks.values():
            assert h["args"][0] == h["path"]
        assert "--release" in hooks["poststop"]["args"]
        # The hook binary lives under the state root (a hostPath the
        # runtime can exec) and is executable.
        assert os.access(hooks["createContainer"]["path"], os.X_OK)

    def test_hbm_budget_is_per_chip_for_multichip_groups(self, tmp_path):
        # Admission must fit tenants within ONE chip's HBM: every tenant
        # runs on every chip of the group, so a 2-chip group does NOT
        # double the budget.
        s = DeviceState(Config.mock(root=str(tmp_path / "root"),
                                    tenancy_agents=True))
        try:
            cfgs = [{
                "parameters": opaque("TpuConfig", sharing={
                    "strategy": "MultiTenancy",
                    "multiTenancy": {"hbmLimit": "12Gi"},
                }),
            }]
            s.prepare(make_claim("c1", ["chip-0", "chip-1"], configs=cfgs))
            d = s._tenancy._dir("c1", "tpu")
            assert preflight_main(["--dir", d, "--hbm-bytes",
                                   str(12 * GI), "--client-id", "a"]) == 0
            # 12Gi committed of a 16Gi (per-chip) budget: no second 12Gi.
            assert preflight_main(["--dir", d, "--hbm-bytes",
                                   str(12 * GI), "--client-id", "b"]) == 1
        finally:
            s.stop()

    def test_preflight_fails_closed_without_agent(self, tmp_path):
        rc = preflight_main(["--dir", str(tmp_path),
                             "--hbm-bytes", "1", "--client-id", "x"])
        assert rc == 1
        # ...but a release during teardown never blocks the runtime.
        assert preflight_main(["--dir", str(tmp_path), "--release",
                               "--client-id", "x"]) == 0

    def test_native_preflight_binary_parity(self, state):
        # The static C++ hook binary (the one real runtimes exec) must
        # enforce identically to the python module.
        native = os.path.join(os.path.dirname(PKG_DIR), "tpulib",
                              "native", "tenancy_preflight")
        if not os.path.exists(native):
            pytest.skip("native preflight not built")
        import subprocess

        self._prepare_tenancy_claim(state, max_clients=1)
        d = state._tenancy._dir("c1", "tpu")

        def run_native(*args):
            return subprocess.run(
                [native, "--dir", d, *args],
                capture_output=True, stdin=subprocess.DEVNULL,
            ).returncode

        assert run_native("--hbm-bytes", "1", "--client-id", "n-a") == 0
        assert run_native("--hbm-bytes", "1", "--client-id", "n-b") == 1
        assert run_native("--release", "--client-id", "n-a") == 0
        assert run_native("--hbm-bytes", "1", "--client-id", "n-b") == 0

    def test_control_plane_not_inside_tenant_mount(self, state):
        # The rw mount tenants get must expose ONLY the shared
        # rendezvous subdir: socket/grants/tombstones outside it, or a
        # tenant could RELEASE a sibling and defeat admission control.
        self._prepare_tenancy_claim(state)
        d = state._tenancy._dir("c1", "tpu")
        spec = state._cdi.read_spec("c1")
        mounts = spec["containerEdits"]["mounts"]
        assert len(mounts) == 1
        assert mounts[0]["hostPath"] == os.path.join(d, "shared")
        shared = os.listdir(os.path.join(d, "shared"))
        assert "agent.sock" not in shared
        assert "clients.json" not in shared
        assert "tenancy.json" in shared  # informational copy

    def test_hook_short_path_survives_plugin_restart(self, tmp_path):
        # The CDI hooks of an already-prepared claim point at the short
        # symlink; a plugin restart (reconcile) must keep it working.
        root = str(tmp_path / "root")
        s1 = DeviceState(Config.mock(root=root, tenancy_agents=True))
        self._prepare_tenancy_claim(s1)
        spec = s1._cdi.read_spec("c1")
        hook = spec["containerEdits"]["hooks"][0]
        short = hook["args"][hook["args"].index("--dir") + 1]
        s1.stop()
        s2 = DeviceState(Config.mock(root=root, tenancy_agents=True))
        try:
            assert preflight_main(["--dir", short, "--hbm-bytes", "1",
                                   "--client-id", "after-restart"]) == 0
        finally:
            s2.stop()

    def test_agent_sigkill_with_held_claim_reowned(self, state):
        """The agent is SIGKILLed while its claim is HELD (not across a
        clean plugin restart): the supervisor watchdog respawns it, the
        respawn reloads grants from disk, and admission continues from
        the pre-kill budget -- a tenant admitted before the kill still
        counts, so the post-kill over-budget tenant is denied.
        Reference analog: test_gpu_robustness.bats MPS-daemon kill."""
        import signal as _signal
        import time as _time

        from k8s_dra_driver_gpu_tpu.kubeletplugin.tenancy_agent import query

        self._prepare_tenancy_claim(state, max_clients=2)
        d = state._tenancy._dir("c1", "tpu")
        assert query(d, "STATUS") == "READY"
        assert query(d, "REGISTER tenant-a 1073741824").startswith("OK")

        with open(os.path.join(d, "agent.pid")) as f:
            pid = int(f.read().split()[0])
        os.kill(pid, _signal.SIGKILL)

        # The watchdog respawns it; the fresh agent rebinds agent.sock
        # and answers READY again without any plugin action.
        deadline = _time.monotonic() + 15
        ready = False
        while _time.monotonic() < deadline:
            try:
                if query(d, "STATUS", timeout=1.0) == "READY":
                    with open(os.path.join(d, "agent.pid")) as f:
                        if int(f.read().split()[0]) != pid:
                            ready = True
                            break
            except OSError:
                pass
            _time.sleep(0.1)
        assert ready, "agent not respawned after SIGKILL"

        # Grant continuity: tenant-a survived on disk, so the budget
        # still counts it -- one more fits, a third is denied.
        members = json.loads(query(d, "MEMBERS"))
        assert "tenant-a" in members["clients"]
        assert query(d, "REGISTER tenant-b 1073741824").startswith("OK")
        assert query(d, "REGISTER tenant-c 1073741824").startswith("DENIED")

        # The claim is still fully operational: unprepare tears the
        # respawned agent down cleanly.
        state.unprepare("c1")
        assert not os.path.isdir(d)

    def test_unprepare_stops_agent_and_removes_dir(self, state):
        self._prepare_tenancy_claim(state)
        d = state._tenancy._dir("c1", "tpu")
        state.unprepare("c1")
        assert not os.path.isdir(d)
        assert not state._tenancy._agents

    def test_plugin_restart_reowns_agent(self, tmp_path):
        root = str(tmp_path / "root")
        s1 = DeviceState(Config.mock(root=root, tenancy_agents=True))
        self._prepare_tenancy_claim(s1)
        s1.stop()  # plugin shutdown kills the agent...
        s2 = DeviceState(Config.mock(root=root, tenancy_agents=True))
        try:
            from k8s_dra_driver_gpu_tpu.kubeletplugin.tenancy_agent import (
                query,
            )

            d = s2._tenancy._dir("c1", "tpu")
            assert query(d, "STATUS") == "READY"  # ...restart re-owns it
        finally:
            s2.stop()

    def test_orphan_tenancy_dir_dropped_on_restart(self, tmp_path):
        root = str(tmp_path / "root")
        s1 = DeviceState(Config.mock(root=root, tenancy_agents=True))
        orphan = os.path.join(root, "tenancy", "ghost-claim")
        os.makedirs(orphan)
        s1.stop()
        s2 = DeviceState(Config.mock(root=root, tenancy_agents=True))
        try:
            assert not os.path.isdir(orphan)
        finally:
            s2.stop()
