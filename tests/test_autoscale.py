"""Serving autoscaler (pkg/autoscale): CRD helpers, the MISO/ParvaGPU
planner with its hysteresis band and CEL priority rules, the
leader-elected re-planning controller (durable ``autoscale``
TransitionPolicy records, crash-at-every-fault-point resume, zero
steady-state kube writes), the TenantProfileStore sliding time window,
and the CRD -> node propagation seam (live Driver + restarted Driver
converge to the same carve-out set; a malformed CRD fails closed)."""

from __future__ import annotations

import pytest

from k8s_dra_driver_gpu_tpu.kubeletplugin import DRIVER_NAME
from k8s_dra_driver_gpu_tpu.kubeletplugin.deviceinfo import (
    AllocatableDevice,
    ChipInfo,
    DeviceKind,
)
from k8s_dra_driver_gpu_tpu.kubeletplugin.device_state import Config
from k8s_dra_driver_gpu_tpu.kubeletplugin.driver import Driver
from k8s_dra_driver_gpu_tpu.kubeletplugin.partitions import (
    consumed_counters,
    shared_counter_sets,
)
from k8s_dra_driver_gpu_tpu.pkg import faults
from k8s_dra_driver_gpu_tpu.pkg.autoscale import (
    AutoscaleController,
    AutoscalePlanner,
    PriorityRule,
    crd_object,
    fingerprint,
    partition_set_from_crd,
    pool_chip_caps,
    select_for_pool,
)
from k8s_dra_driver_gpu_tpu.pkg.autoscale import crd as crdmod
from k8s_dra_driver_gpu_tpu.pkg.autoscale.planner import (
    TENANT_DEMAND_HBM_ANNOTATION,
)
from k8s_dra_driver_gpu_tpu.pkg.kubeclient import FakeKubeClient
from k8s_dra_driver_gpu_tpu.pkg.metrics import AutoscaleMetrics
from k8s_dra_driver_gpu_tpu.pkg.partition import (
    TENANT_PROFILE_ANNOTATION,
    PartitionSet,
    PartitionSpecError,
    SizingPolicy,
    TenantProfileStore,
)
from k8s_dra_driver_gpu_tpu.pkg.scheduler import DraScheduler
from k8s_dra_driver_gpu_tpu.tpulib.binding import (
    EnumerateOptions,
    PyTpuLib,
)
from tests.fake_kube import CountingKube

RES = ("resource.k8s.io", "v1")
CRD = ("resource.tpu.dra", "v1beta1", "partitionsets")
GIB = 1 << 30
GATES = ("DynamicSubSlice=true,TimeSlicingSettings=true,"
         "MultiTenancySupport=true,TenantPartitioning=true")

_LIB = PyTpuLib()
_OPTS = EnumerateOptions(mock_topology="v5e-4")
HOST = _LIB.enumerate(_OPTS)
CHIP_HBM = HOST.hbm_bytes_per_chip


def publish_chip_fleet(fake, nodes: int = 1) -> None:
    """Publish plain whole-chip slices (the counter source the planner
    budgets against)."""
    for i in range(nodes):
        devs = []
        for chip in HOST.chips:
            dev = AllocatableDevice(
                kind=DeviceKind.CHIP, chip=ChipInfo(chip=chip, host=HOST))
            entry = dev.to_dra_device()
            entry["consumesCounters"] = consumed_counters(dev, HOST)
            devs.append(entry)
        fake.create(*RES, "resourceslices", {
            "apiVersion": "resource.k8s.io/v1", "kind": "ResourceSlice",
            "metadata": {"name": f"node-{i}-{DRIVER_NAME}"},
            "spec": {
                "driver": DRIVER_NAME, "nodeName": f"node-{i}",
                "pool": {"name": f"node-{i}", "generation": 1,
                         "resourceSliceCount": 1},
                "sharedCounters": shared_counter_sets(HOST),
                "devices": devs,
            },
        })


def make_controller(kube, root, **kw) -> AutoscaleController:
    kw.setdefault("sustain_s", 0.0)
    kw.setdefault("cooldown_s", 0.0)
    return AutoscaleController(kube, root, **kw)


def run_to_convergence(ctrl, passes: int = 6) -> dict:
    last = {}
    for _ in range(passes):
        last = ctrl.sync_once()
        if not ctrl.busy() and (last["converged"] or last["deferred"]):
            break
    return last


def tenant_claim(fake, name: str, tenant: str, hbm: int,
                 allocated: bool = False) -> None:
    obj = {
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
        "metadata": {"name": name, "namespace": "default",
                     "annotations": {
                         TENANT_PROFILE_ANNOTATION: tenant,
                         TENANT_DEMAND_HBM_ANNOTATION: str(hbm),
                     }},
        "spec": {"devices": {"requests": [{"name": "t"}]}},
    }
    if allocated:
        obj["status"] = {"allocation": {"devices": {"results": []}}}
    fake.create(*RES, "resourceclaims", obj, namespace="default")


# -- CRD helpers --------------------------------------------------------------


class TestCrd:
    def test_round_trip(self):
        ps = PartitionSet.from_dict({"profiles": [
            {"name": "web-s8", "subslice": "1x1", "maxTenants": 8}],
            "pools": ["node-*"]})
        obj = crd_object("tpu-dra-autoscale", ps,
                         priority_rules=(PriorityRule(
                             "tenant.key == 'interactive'", 100),))
        parsed, rules = partition_set_from_crd(obj)
        assert parsed == ps
        assert rules[0].priority == 100
        assert crdmod.is_managed(obj)
        assert crdmod.revision_of(obj) == 1

    def test_malformed_spec_raises(self):
        with pytest.raises(PartitionSpecError):
            partition_set_from_crd({"metadata": {"name": "x"}})
        with pytest.raises(PartitionSpecError):
            partition_set_from_crd({"spec": {"profiles": [
                {"name": "BAD NAME", "subslice": "1x1"}]}})

    def test_malformed_priority_rule_raises(self):
        with pytest.raises(PartitionSpecError):
            partition_set_from_crd({"spec": {
                "profiles": [],
                "priorityRules": [{"selector": "tenant.key =="}]}})
        with pytest.raises(PartitionSpecError):
            partition_set_from_crd({"spec": {
                "profiles": [], "priorityRules": [{"priority": 3}]}})

    def test_priority_rule_matching(self):
        rule = PriorityRule("tenant.hbmBytes > 4000000000", 10)
        assert rule.matches("big", 6 * GIB, 1)
        assert not rule.matches("small", 1 * GIB, 1)
        # Eval errors mean "no match", never a crash.
        assert not PriorityRule("tenant.nope.deeper == 1", 10).matches(
            "x", 1, 1)

    def test_select_for_pool_orders_by_name(self):
        ours = crd_object("tpu-dra-autoscale", PartitionSet.from_dict(
            {"profiles": [{"name": "a-s8", "subslice": "1x1",
                           "maxTenants": 8}]}))
        manual = crd_object("00-manual", PartitionSet.from_dict(
            {"profiles": [{"name": "b-s1", "subslice": "1x1"}]}),
            managed=False)
        outcome, payload, obj = select_for_pool([ours, manual], "node-0")
        assert outcome == "ok"
        ps, _rules, fp = payload
        assert ps.profiles[0].name == "b-s1"
        assert obj["metadata"]["name"] == "00-manual"
        assert fp == fingerprint(manual["spec"])

    def test_select_respects_pool_globs(self):
        scoped = crd_object("scoped", PartitionSet.from_dict(
            {"profiles": [], "pools": ["pool-a*"]}))
        outcome, _, _ = select_for_pool([scoped], "pool-b7")
        assert outcome == "none"
        outcome, _, _ = select_for_pool([scoped], "pool-a3")
        assert outcome == "ok"

    def test_select_malformed_winner_fails_closed(self):
        # The WINNING object being malformed is reported -- never
        # silently skipped in favor of a lower-ranked one.
        bad = {"apiVersion": "resource.tpu.dra/v1beta1",
               "kind": "PartitionSet",
               "metadata": {"name": "00-bad"},
               "spec": {"profiles": [{"name": "BAD NAME",
                                      "subslice": "1x1"}]}}
        good = crd_object("zz-good", PartitionSet.from_dict(
            {"profiles": []}))
        outcome, err, obj = select_for_pool([good, bad], "node-0")
        assert outcome == "malformed"
        assert "BAD NAME" in err
        assert obj["metadata"]["name"] == "00-bad"


# -- TenantProfileStore sliding window (satellite) ----------------------------


class TestProfileWindow:
    def test_burst_then_decay_shrinks_sized_profile(self):
        """The regression the satellite names: a demand burst followed
        by decay must shrink the sized profile once the burst's
        samples age out of the TPU_DRA_PROFILE_WINDOW_S window."""
        store = TenantProfileStore(defaults={}, window_s=60.0)
        for _ in range(50):  # the burst: 12 GiB working sets at t=0
            store.observe("web", 12 * GIB, now=1000.0)
        planner = AutoscalePlanner()
        cat = planner._catalog("web", CHIP_HBM, HOST.cores_per_chip,
                               (1, 2, 4, 8))
        big = SizingPolicy().pick(
            store.demand("web", now=1010.0), cat)
        assert big.profile.max_tenants == 1  # 12Gi of a 16Gi chip
        for _ in range(20):  # decay: small working sets at t+100
            store.observe("web", int(1.5 * GIB), now=1100.0)
        small = SizingPolicy().pick(
            store.demand("web", now=1105.0), cat)
        assert small.profile.max_tenants == 8  # 2Gi budget covers 1.5Gi

    def test_all_aged_out_falls_back_to_last_sample(self):
        store = TenantProfileStore(defaults={}, window_s=10.0)
        store.observe("web", 3 * GIB, now=0.0)
        d = store.demand("web", now=1000.0)
        assert d is not None and d.hbm_bytes == 3 * GIB

    def test_window_zero_is_all_history(self):
        store = TenantProfileStore(defaults={}, window_s=0.0)
        store.observe("web", 8 * GIB, now=0.0)
        store.observe("web", 1 * GIB, now=1e9)
        d = store.demand("web", percentile=0.99, now=2e9)
        assert d.hbm_bytes == 8 * GIB

    def test_fresh_tenants_excludes_aged_keys(self):
        store = TenantProfileStore(defaults={}, window_s=60.0)
        store.observe("old", GIB, now=0.0)
        store.observe("new", GIB, now=1000.0)
        assert store.fresh_tenants(now=1010.0) == ["new"]

    def test_percentiles_surface(self):
        store = TenantProfileStore(defaults={}, window_s=0.0)
        for i in range(100):
            store.observe("web", i * GIB)
        pct = store.percentiles()
        assert pct["web"]["p50_hbm_bytes"] == 49 * GIB
        assert pct["web"]["p95_hbm_bytes"] == 94 * GIB


# -- planner ------------------------------------------------------------------


class TestPlanner:
    def _store(self, tenant="web", hbm=int(1.5 * GIB), n=40):
        store = TenantProfileStore(defaults={})
        for _ in range(n):
            store.observe(tenant, hbm)
        return store

    def test_sizes_smallest_satisfying(self):
        plan = AutoscalePlanner().plan(
            self._store(), PartitionSet.from_dict({}),
            chip_hbm=CHIP_HBM, cores_per_chip=HOST.cores_per_chip)
        assert plan.changed
        names = [p.name for p in plan.desired.profiles]
        assert names == ["web-s8"]

    def test_no_counters_keeps_active_verbatim(self):
        active = PartitionSet.from_dict({"profiles": [
            {"name": "web-s8", "subslice": "1x1", "maxTenants": 8}]})
        plan = AutoscalePlanner().plan(self._store(), active,
                                       chip_hbm=0)
        assert not plan.changed and plan.desired == active

    def test_upsize_is_urgent(self):
        active = PartitionSet.from_dict({"profiles": [
            {"name": "web-s8", "subslice": "1x1", "maxTenants": 8}]})
        plan = AutoscalePlanner().plan(
            self._store(hbm=3 * GIB), active,
            chip_hbm=CHIP_HBM, cores_per_chip=HOST.cores_per_chip)
        assert plan.changed and plan.urgent
        assert [p.name for p in plan.desired.profiles] == ["web-s4"]
        assert plan.decisions["web"]["action"] == "upsize"

    def test_hysteresis_band_blocks_boundary_repack(self):
        # Active s4 (4Gi budget); demand 1.9Gi. s8's 2Gi budget would
        # fit, but only with 5% headroom -- inside the 10% band, so
        # the layout must NOT flap.
        active = PartitionSet.from_dict({"profiles": [
            {"name": "web-s4", "subslice": "1x1", "maxTenants": 4}]})
        plan = AutoscalePlanner(band=0.1).plan(
            self._store(hbm=int(1.9 * GIB)), active,
            chip_hbm=CHIP_HBM, cores_per_chip=HOST.cores_per_chip)
        assert not plan.changed
        assert plan.decisions["web"]["action"] == "keep"

    def test_clear_headroom_repacks_non_urgent(self):
        active = PartitionSet.from_dict({"profiles": [
            {"name": "web-s4", "subslice": "1x1", "maxTenants": 4}]})
        plan = AutoscalePlanner(band=0.1).plan(
            self._store(hbm=int(1.2 * GIB)), active,
            chip_hbm=CHIP_HBM, cores_per_chip=HOST.cores_per_chip)
        assert plan.changed and not plan.urgent
        assert [p.name for p in plan.desired.profiles] == ["web-s8"]
        assert plan.decisions["web"]["action"] == "repack"

    def test_cel_priority_packs_away_from_oversubscription(self):
        rules = (PriorityRule("tenant.key == 'interactive'", 100),)
        store = self._store(tenant="interactive")
        plan = AutoscalePlanner().plan(
            store, PartitionSet.from_dict({}), rules=rules,
            chip_hbm=CHIP_HBM, cores_per_chip=HOST.cores_per_chip)
        # 1.5Gi demand would pack 8/chip -- but the priority rule
        # forces a dedicated (maxTenants == 1) profile.
        assert [p.name for p in plan.desired.profiles] == \
            ["interactive-s1"]
        assert plan.decisions["interactive"]["priority"] == 100

    def test_priority_isolation_off_shared_is_urgent(self):
        rules = (PriorityRule("tenant.key == 'interactive'", 100),)
        active = PartitionSet.from_dict({"profiles": [
            {"name": "interactive-s8", "subslice": "1x1",
             "maxTenants": 8}]})
        plan = AutoscalePlanner().plan(
            self._store(tenant="interactive"), active, rules=rules,
            chip_hbm=CHIP_HBM, cores_per_chip=HOST.cores_per_chip)
        assert plan.changed and plan.urgent
        assert plan.decisions["interactive"]["action"] == "isolate"

    def test_aged_out_tenant_profile_retires(self):
        store = TenantProfileStore(defaults={}, window_s=60.0)
        store.observe("gone", GIB, now=0.0)
        active = PartitionSet.from_dict({"profiles": [
            {"name": "gone-s8", "subslice": "1x1", "maxTenants": 8}]})
        plan = AutoscalePlanner().plan(
            store, active, chip_hbm=CHIP_HBM,
            cores_per_chip=HOST.cores_per_chip, now=1000.0)
        assert plan.changed and not plan.urgent
        assert plan.desired.profiles == ()

    def test_live_tenant_profile_retained_despite_aged_samples(self):
        store = TenantProfileStore(defaults={}, window_s=60.0)
        store.observe("web", GIB, now=0.0)
        active = PartitionSet.from_dict({"profiles": [
            {"name": "web-s8", "subslice": "1x1", "maxTenants": 8}]})
        plan = AutoscalePlanner().plan(
            store, active, chip_hbm=CHIP_HBM,
            cores_per_chip=HOST.cores_per_chip,
            live_tenants={"web"}, now=1000.0)
        # The last-sample fallback keeps the demand alive, sizing
        # still lands on s8 -> no change.
        assert not plan.changed

    def test_pool_chip_caps_reads_published_counters(self):
        fake = FakeKubeClient()
        publish_chip_fleet(fake)
        hbm, cores = pool_chip_caps(fake.list(*RES, "resourceslices"))
        assert hbm == CHIP_HBM
        assert cores == HOST.cores_per_chip


# -- controller ---------------------------------------------------------------


class TestController:
    def _fixture(self, tmp_path, tenants=40, hbm=int(1.5 * GIB)):
        fake = FakeKubeClient()
        publish_chip_fleet(fake)
        counted = CountingKube(fake)
        ctrl = make_controller(counted, str(tmp_path / "as"))
        for _ in range(tenants):
            ctrl.store.observe("web", hbm)
        return fake, counted, ctrl

    def test_rollout_and_steady_state_zero_writes(self, tmp_path):
        fake, counted, ctrl = self._fixture(tmp_path)
        run_to_convergence(ctrl)
        crds = fake.list(*CRD)
        assert len(crds) == 1
        ps, _rules = partition_set_from_crd(crds[0])
        assert [p.name for p in ps.profiles] == ["web-s8"]
        assert not ctrl.busy()
        # Converged passes: ZERO kube writes.
        w0 = counted.writes
        for _ in range(3):
            out = ctrl.sync_once()
            assert out["converged"] == 1
        assert counted.writes == w0

    def test_replan_on_demand_shift(self, tmp_path):
        fake, _counted, ctrl = self._fixture(tmp_path)
        run_to_convergence(ctrl)
        for _ in range(200):  # demand grows past the 2Gi s8 budget
            ctrl.store.observe("web", 6 * GIB)
        run_to_convergence(ctrl)
        ps, _ = partition_set_from_crd(fake.list(*CRD)[0])
        assert [p.name for p in ps.profiles] == ["web-s2"]
        assert crdmod.revision_of(fake.list(*CRD)[0]) == 2

    def test_sustain_defers_non_urgent_repack(self, tmp_path):
        fake = FakeKubeClient()
        publish_chip_fleet(fake)
        ctrl = make_controller(fake, str(tmp_path / "as"),
                               sustain_s=3600.0)
        # Seed an active layout at s4, then demand that would repack
        # to s8 (non-urgent): the sustain window must defer it.
        fake.create(*CRD, crd_object(
            "tpu-dra-autoscale", PartitionSet.from_dict({"profiles": [
                {"name": "web-s4", "subslice": "1x1",
                 "maxTenants": 4}]})))
        for _ in range(40):
            ctrl.store.observe("web", int(1.2 * GIB))
        out = ctrl.sync_once()
        assert out["deferred"] == 1 and out["planned"] == 0
        assert fake.list(*CRD)[0]["spec"]["profiles"][0]["name"] == \
            "web-s4"

    def test_urgent_upsize_skips_sustain(self, tmp_path):
        fake = FakeKubeClient()
        publish_chip_fleet(fake)
        ctrl = make_controller(fake, str(tmp_path / "as"),
                               sustain_s=3600.0)
        fake.create(*CRD, crd_object(
            "tpu-dra-autoscale", PartitionSet.from_dict({"profiles": [
                {"name": "web-s8", "subslice": "1x1",
                 "maxTenants": 8}]})))
        for _ in range(40):
            ctrl.store.observe("web", 3 * GIB)
        out = ctrl.sync_once()
        assert out["planned"] == 1

    def test_fleet_pending_ring_skips_sustain(self, tmp_path):
        """The fleet pending-demand ring input: sustained pending
        claims while a repack would add slot capacity must fire NOW
        instead of idling out the sustain window."""
        from k8s_dra_driver_gpu_tpu.pkg.fleetstate import (
            FleetAggregator,
        )

        fake = FakeKubeClient()
        publish_chip_fleet(fake)
        fleet = FleetAggregator()
        empty_snap = type("S", (), {"candidates": []})()
        for _ in range(3):
            fleet.observe_pass(empty_snap, None, pending_claims=5)
        ctrl = make_controller(fake, str(tmp_path / "as"),
                               sustain_s=3600.0, fleet=fleet)
        fake.create(*CRD, crd_object(
            "tpu-dra-autoscale", PartitionSet.from_dict({"profiles": [
                {"name": "web-s4", "subslice": "1x1",
                 "maxTenants": 4}]})))
        # Repack-level demand (non-urgent on its own) + a PENDING
        # tenant + the fleet ring showing sustained pending.
        tenant_claim(fake, "c1", "web", int(1.2 * GIB),
                     allocated=False)
        for _ in range(40):
            ctrl.store.observe("web", int(1.2 * GIB))
        out = ctrl.sync_once()
        assert out["planned"] == 1

    def test_manual_override_freezes_planning(self, tmp_path):
        fake, counted, ctrl = self._fixture(tmp_path)
        run_to_convergence(ctrl)
        obj = fake.list(*CRD)[0]
        fake.patch(*CRD, obj["metadata"]["name"], {
            "metadata": {"annotations": {
                crdmod.MANAGED_ANNOTATION: "false"}}})
        for _ in range(200):
            ctrl.store.observe("web", 6 * GIB)  # would normally replan
        w0 = counted.writes
        out = ctrl.sync_once()
        assert out["deferred"] == 1 and out["planned"] == 0
        assert counted.writes == w0

    def test_concurrent_operator_edit_supersedes(self, tmp_path):
        fake, _counted, ctrl = self._fixture(tmp_path)
        metrics = AutoscaleMetrics()
        ctrl.metrics = metrics
        ctrl.sync_once()  # planned + applied (record now Applying)
        assert ctrl.busy()
        # Operator takes over mid-rollout: rewrites the spec AND flips
        # the managed annotation (the manual-override workflow).
        fake.patch(*CRD, "tpu-dra-autoscale", {
            "metadata": {"annotations": {
                crdmod.MANAGED_ANNOTATION: "false"}},
            "spec": {"profiles": [
                {"name": "manual-s2", "subslice": "1x1",
                 "maxTenants": 2}], "pools": []}})
        out = ctrl.sync_once()
        assert out["superseded"] == 1
        assert not ctrl.busy()
        # Operator content stands and planning is frozen.
        assert fake.list(*CRD)[0]["spec"]["profiles"][0]["name"] == \
            "manual-s2"
        out = ctrl.sync_once()
        assert out["deferred"] == 1 and out["planned"] == 0
        assert metrics.superseded._value.get() == 1

    def test_managed_flip_mid_plan_never_stomped(self, tmp_path):
        """An operator flipping the managed annotation off while a
        Planned record is in flight wins: the apply stage retires the
        rollout as superseded instead of merge-patching the
        annotation back to \"true\" (which would silently erase the
        override)."""
        fake, counted, ctrl = self._fixture(tmp_path)
        run_to_convergence(ctrl)
        # Arm a second rollout but stop it at Planned: fail the apply
        # stage's fresh read once so the record stays Planned.
        for _ in range(200):
            ctrl.store.observe("web", 6 * GIB)
        faults.arm("autoscale.apply", mode="error", count=1)
        try:
            try:
                ctrl.sync_once()
            except Exception:  # noqa: BLE001 - injected
                pass
        finally:
            faults.reset()
        assert ctrl.busy()  # Planned record in flight
        # Operator takes manual control BEFORE the write lands.
        fake.patch(*CRD, "tpu-dra-autoscale", {
            "metadata": {"annotations": {
                crdmod.MANAGED_ANNOTATION: "false"}}})
        spec_before = fake.list(*CRD)[0]["spec"]
        out = ctrl.sync_once()
        assert out["superseded"] == 1 and out["applied"] == 0
        assert not ctrl.busy()
        live = fake.list(*CRD)[0]
        # Neither the annotation nor the spec was stomped.
        assert not crdmod.is_managed(live)
        assert live["spec"] == spec_before

    def test_malformed_managed_crd_defers(self, tmp_path):
        fake, counted, ctrl = self._fixture(tmp_path)
        run_to_convergence(ctrl)
        fake.patch(*CRD, "tpu-dra-autoscale", {"spec": {"profiles": [
            {"name": "BAD NAME", "subslice": "1x1"}]}})
        w0 = counted.writes
        out = ctrl.sync_once()
        assert out["deferred"] == 1
        assert counted.writes == w0

    def test_claim_annotations_feed_store_and_age_out(self, tmp_path):
        fake = FakeKubeClient()
        publish_chip_fleet(fake)
        ctrl = make_controller(fake, str(tmp_path / "as"))
        ctrl.store.window_s = 60.0
        tenant_claim(fake, "c1", "api", 5 * GIB, allocated=True)
        ctrl.sync_once()
        d = ctrl.store.demand("api")
        assert d is not None and d.hbm_bytes == 5 * GIB

    def test_pending_tenant_is_urgent(self, tmp_path):
        fake = FakeKubeClient()
        publish_chip_fleet(fake)
        ctrl = make_controller(fake, str(tmp_path / "as"),
                               sustain_s=3600.0)
        tenant_claim(fake, "c1", "api", 2 * GIB, allocated=False)
        out = ctrl.sync_once()
        assert out["planned"] == 1  # new pending tenant fires NOW

    @pytest.mark.parametrize("fault", [
        "autoscale.sync", "autoscale.plan", "autoscale.apply",
        "autoscale.confirm"])
    def test_crash_at_every_fault_point_resumes_to_same_plan(
            self, tmp_path, fault):
        """A controller crash at ANY fault point resumes idempotently:
        a fresh controller on the same root converges the CRD to the
        same content an uncrashed run produces."""
        # Reference run (no faults).
        ref_fake = FakeKubeClient()
        publish_chip_fleet(ref_fake)
        ref = make_controller(ref_fake, str(tmp_path / "ref"))
        for _ in range(40):
            ref.store.observe("web", int(1.5 * GIB))
        run_to_convergence(ref)
        ref_fp = fingerprint(ref_fake.list(*CRD)[0]["spec"])

        fake = FakeKubeClient()
        publish_chip_fleet(fake)
        root = str(tmp_path / "crash")
        ctrl = make_controller(fake, root)
        for _ in range(40):
            ctrl.store.observe("web", int(1.5 * GIB))
        faults.arm(fault, mode="error", count=1)
        try:
            crashed = False
            for _ in range(6):
                try:
                    ctrl.sync_once()
                except Exception:  # noqa: BLE001 - injected
                    crashed = True
                    break
            assert crashed, f"{fault} never fired"
        finally:
            faults.reset()
        # The controller "process" died; a fresh one on the same root
        # resumes from the durable records.
        resumed = make_controller(fake, root)
        for _ in range(40):
            resumed.store.observe("web", int(1.5 * GIB))
        run_to_convergence(resumed)
        assert not resumed.busy()
        crds = fake.list(*CRD)
        assert len(crds) == 1
        assert fingerprint(crds[0]["spec"]) == ref_fp

    def test_event_mode_rollout_needs_no_resync(self, tmp_path):
        """The liveness chain: plan+apply land in one pass, and the
        CRD write's own partitionsets informer event drives the
        confirm stage -- a rollout completes without waiting out the
        safety resync (set to an hour here on purpose)."""
        fake = FakeKubeClient()
        publish_chip_fleet(fake)
        sched = DraScheduler(fake, workers=1, resync_period=3600.0)
        ctrl = make_controller(fake, str(tmp_path / "as"))
        sched.attach_autoscaler(ctrl)
        for _ in range(40):
            ctrl.store.observe("web", int(1.5 * GIB))
        sched.start_event_driven()
        try:
            import time as _time

            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline:
                assert sched.drain(10)
                if fake.list(*CRD) and not ctrl.busy():
                    break
                _time.sleep(0.02)
            assert not ctrl.busy(), "rollout stalled waiting on resync"
            ps, _ = partition_set_from_crd(fake.list(*CRD)[0])
            assert [p.name for p in ps.profiles] == ["web-s8"]
        finally:
            sched.stop()

    def test_rides_scheduler_loop(self, tmp_path):
        fake = FakeKubeClient()
        publish_chip_fleet(fake)
        sched = DraScheduler(fake)
        ctrl = make_controller(fake, str(tmp_path / "as"))
        sched.attach_autoscaler(ctrl)
        for _ in range(40):
            ctrl.store.observe("web", int(1.5 * GIB))
        for _ in range(3):
            sched.sync_once()
        ps, _ = partition_set_from_crd(fake.list(*CRD)[0])
        assert [p.name for p in ps.profiles] == ["web-s8"]
        # The fleet snapshot surfaces what the planner saw.
        snap = sched.fleet.snapshot()
        assert "web" in snap["tenant_demand"]
        assert snap["pending_history"], "pending ring must be fed"


# -- CRD -> node propagation seam (satellite) ---------------------------------


def _node_config(root: str) -> Config:
    cfg = Config.mock(root=root, gates=GATES,
                      partition_set=PartitionSet.from_dict({}))
    cfg.pool_name = "node-0"
    return cfg


def _pt_devices(driver: Driver) -> list[str]:
    return sorted(n for n, d in driver.state.allocatable.items()
                  if d.kind == DeviceKind.PARTITION)


class TestNodeSeam:
    def _crd(self, slots=8, name="tpu-dra-autoscale", revision=1):
        return crd_object(name, PartitionSet.from_dict({"profiles": [
            {"name": f"web-s{slots}", "subslice": "1x1",
             "maxTenants": slots}]}), revision=revision)

    def test_live_driver_converges_on_crd_update(self, tmp_path):
        fake = FakeKubeClient()
        drv = Driver(_node_config(str(tmp_path / "n0")), fake, "node-0",
                     enable_health_monitor=False)
        drv.start()
        try:
            assert _pt_devices(drv) == []
            fake.create(*CRD, self._crd(slots=8))
            assert _pt_devices(drv) == [
                f"pt-web-s8-{k}" for k in range(len(HOST.chips))]
            # Published through the diff: the partition devices are on
            # the apiserver too.
            slices = fake.list(*RES, "resourceslices")
            names = {d["name"] for s in slices
                     for d in s["spec"]["devices"]}
            assert "pt-web-s8-0" in names
            # Re-plan via CRD update converges live.
            fake.update(*CRD, "tpu-dra-autoscale",
                        self._crd(slots=4, revision=2))
            assert _pt_devices(drv) == [
                f"pt-web-s4-{k}" for k in range(len(HOST.chips))]
        finally:
            drv.stop()

    def test_restarted_driver_converges_to_same_set(self, tmp_path):
        fake = FakeKubeClient()
        fake.create(*CRD, self._crd(slots=8))
        root = str(tmp_path / "n0")
        drv = Driver(_node_config(root), fake, "node-0",
                     enable_health_monitor=False)
        drv.start()
        live_set = _pt_devices(drv)
        live_slices = {s["metadata"]["name"]:
                       sorted(d["name"] for d in s["spec"]["devices"])
                       for s in fake.list(*RES, "resourceslices")}
        drv.stop()
        assert live_set, "live driver saw no partition devices"
        # Fresh process, same root: the watcher's initial reconcile
        # must converge to the SAME carve-out set.
        drv2 = Driver(_node_config(root), fake, "node-0",
                      enable_health_monitor=False)
        drv2.start()
        try:
            assert _pt_devices(drv2) == live_set
            slices2 = {s["metadata"]["name"]:
                       sorted(d["name"] for d in s["spec"]["devices"])
                       for s in fake.list(*RES, "resourceslices")}
            assert slices2 == live_slices
        finally:
            drv2.stop()

    def test_malformed_crd_keeps_last_good_plan(self, tmp_path):
        fake = FakeKubeClient()
        fake.create(*CRD, self._crd(slots=8))
        drv = Driver(_node_config(str(tmp_path / "n0")), fake, "node-0",
                     enable_health_monitor=False)
        drv.start()
        try:
            good = _pt_devices(drv)
            assert good
            fake.update(*CRD, "tpu-dra-autoscale", {
                "apiVersion": "resource.tpu.dra/v1beta1",
                "kind": "PartitionSet",
                "metadata": {"name": "tpu-dra-autoscale"},
                "spec": {"profiles": [{"name": "BAD NAME",
                                       "subslice": "nope"}]}})
            assert _pt_devices(drv) == good  # fail closed
            assert drv.partition_watcher.last_error
            assert drv.partition_watcher.failed_total >= 1
            # A later good update recovers.
            fake.update(*CRD, "tpu-dra-autoscale",
                        self._crd(slots=4, revision=3))
            assert _pt_devices(drv) == [
                f"pt-web-s4-{k}" for k in range(len(HOST.chips))]
            assert drv.partition_watcher.last_error is None
        finally:
            drv.stop()

    def test_malformed_counter_dedupes_and_revert_clears_error(
            self, tmp_path):
        """One persistent malformed CRD counts ONCE (not once per
        event/resync), and reverting it to the already-applied content
        clears last_error on the converged no-op path."""
        fake = FakeKubeClient()
        good = self._crd(slots=8)
        fake.create(*CRD, good)
        drv = Driver(_node_config(str(tmp_path / "n0")), fake, "node-0",
                     enable_health_monitor=False)
        drv.start()
        try:
            watcher = drv.partition_watcher
            fake.update(*CRD, "tpu-dra-autoscale", {
                "apiVersion": "resource.tpu.dra/v1beta1",
                "kind": "PartitionSet",
                "metadata": {"name": "tpu-dra-autoscale"},
                "spec": {"profiles": [{"name": "BAD NAME",
                                       "subslice": "nope"}]}})
            assert watcher.failed_total == 1
            for _ in range(3):  # resync-like re-reconciles
                watcher.reconcile()
            assert watcher.failed_total == 1  # deduped on error text
            # Operator reverts to the content already applied: the
            # converged no-op must clear the stale error.
            fake.update(*CRD, "tpu-dra-autoscale", good)
            assert watcher.last_error is None
            assert _pt_devices(drv) == [
                f"pt-web-s8-{k}" for k in range(len(HOST.chips))]
        finally:
            drv.stop()

    def test_crd_delete_reverts_to_bootstrap(self, tmp_path):
        fake = FakeKubeClient()
        bootstrap = PartitionSet.from_dict({"profiles": [
            {"name": "boot-s2", "subslice": "1x1", "maxTenants": 2}]})
        cfg = Config.mock(root=str(tmp_path / "n0"), gates=GATES,
                          partition_set=bootstrap)
        cfg.pool_name = "node-0"
        drv = Driver(cfg, fake, "node-0", enable_health_monitor=False)
        drv.start()
        try:
            assert _pt_devices(drv) == [
                f"pt-boot-s2-{k}" for k in range(len(HOST.chips))]
            fake.create(*CRD, self._crd(slots=8))
            assert _pt_devices(drv) == [
                f"pt-web-s8-{k}" for k in range(len(HOST.chips))]
            fake.delete(*CRD, "tpu-dra-autoscale")
            assert _pt_devices(drv) == [
                f"pt-boot-s2-{k}" for k in range(len(HOST.chips))]
        finally:
            drv.stop()

    def test_watch_opt_out_restores_file_only_behavior(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_DRA_PARTITION_WATCH", "0")
        fake = FakeKubeClient()
        fake.create(*CRD, self._crd(slots=8))
        drv = Driver(_node_config(str(tmp_path / "n0")), fake, "node-0",
                     enable_health_monitor=False)
        drv.start()
        try:
            assert drv.partition_watcher is None
            assert _pt_devices(drv) == []
        finally:
            drv.stop()
