"""Tests for the tpulib device layer (native C++ + Python backends).

The parity class is the TPU analog of the reference's mock-NVML fidelity
requirement (SURVEY.md §4.4): both backends must agree exactly so tests
exercising either are equivalent.
"""

import dataclasses
import os

import pytest

from k8s_dra_driver_gpu_tpu.tpulib.binding import (
    EnumerateOptions,
    NativeTpuLib,
    PyTpuLib,
    TpuLibError,
    load,
)

NATIVE_AVAILABLE = True
try:
    NativeTpuLib()
except (TpuLibError, OSError):
    NATIVE_AVAILABLE = False

BACKENDS = [PyTpuLib()] + ([NativeTpuLib()] if NATIVE_AVAILABLE else [])


@pytest.fixture(params=[b.name for b in BACKENDS])
def lib(request):
    return {b.name: b for b in BACKENDS}[request.param]


class TestEnumerate:
    def test_v5e4_single_host(self, lib):
        h = lib.enumerate(EnumerateOptions(mock_topology="v5e-4"))
        assert h.platform == "v5e"
        assert h.topology == "2x2"
        assert h.num_hosts == 1
        assert h.cores_per_chip == 1
        assert len(h.chips) == 4
        assert [c.ici_coords for c in h.chips] == [
            (0, 0, 0), (1, 0, 0), (0, 1, 0), (1, 1, 0)
        ]
        assert h.chips[0].devpath == "/dev/accel0"
        assert h.source == "mock"

    def test_v5p16_multi_host_coords(self, lib):
        # v5p-16 = 16 TensorCores = 8 chips = 2x2x2, 2 hosts of 4.
        h0 = lib.enumerate(EnumerateOptions(mock_topology="v5p-16", worker_id=0))
        h1 = lib.enumerate(EnumerateOptions(mock_topology="v5p-16", worker_id=1))
        assert h0.topology == "2x2x2"
        assert h0.num_slice_chips == 8
        assert h0.num_hosts == 2
        # Worker 1's block sits at z=1.
        assert [c.ici_coords for c in h1.chips] == [
            (0, 0, 1), (1, 0, 1), (0, 1, 1), (1, 1, 1)
        ]
        # All 8 chip coords across hosts are unique and fill the grid.
        coords = {c.ici_coords for c in h0.chips} | {c.ici_coords for c in h1.chips}
        assert len(coords) == 8

    def test_v5p32_is_16_chips(self, lib):
        # v5p type suffix counts cores: v5p-32 = 16 chips = 2x2x4, 4 hosts.
        h = lib.enumerate(EnumerateOptions(mock_topology="v5p-32"))
        assert h.num_slice_chips == 16
        assert h.topology == "2x2x4"
        assert h.num_hosts == 4

    def test_devfs_fake_tree(self, lib, tmp_path):
        dev = tmp_path / "dev"
        dev.mkdir()
        for i in range(4):
            (dev / f"accel{i}").touch()
        sys = tmp_path / "sys"
        for i in range(4):
            d = sys / "class" / "accel" / f"accel{i}"
            d.mkdir(parents=True)
            (d / "device").mkdir()
            (d / "device" / "numa_node").write_text("0\n")
        h = lib.enumerate(
            EnumerateOptions(dev_root=str(dev), sys_root=str(sys))
        )
        assert h.source == "devfs"
        assert len(h.chips) == 4
        assert h.chips[2].devpath == str(dev / "accel2")
        assert h.chips[0].numa_node == 0

    def test_devfs_sparse_indices_stay_in_grid(self, lib, tmp_path):
        # accel1 missing (failed chip): remaining chips map by position,
        # inside the reduced grid.
        dev = tmp_path / "dev"
        dev.mkdir()
        (dev / "accel0").touch()
        (dev / "accel2").touch()
        h = lib.enumerate(
            EnumerateOptions(dev_root=str(dev), sys_root=str(tmp_path))
        )
        dims = h.topology_dims + (1,) * (3 - len(h.topology_dims))
        for c in h.chips:
            assert all(0 <= c.ici_coords[i] < dims[i] for i in range(3)), c

    def test_devfs_empty(self, lib, tmp_path):
        h = lib.enumerate(EnumerateOptions(dev_root=str(tmp_path)))
        assert h.source == "none"
        assert h.chips == ()


class TestSubSliceProfiles:
    def test_v5p_profiles(self, lib):
        profs = {p.name: p for p in lib.subslice_profiles(
            EnumerateOptions(mock_topology="v5p-8"))}
        # Megacore chips expose a single-TensorCore profile.
        assert profs["1c"].cores == 1
        assert profs["1c"].placements == tuple(range(8))
        assert profs["1x1x1"].chips == 1
        assert profs["1x1x1"].placements == (0, 1, 2, 3)
        assert profs["2x1x1"].placements == (0, 2)
        assert profs["1x2x1"].placements == (0, 1)
        assert profs["2x2x1"].placements == (0,)

    def test_two_chip_3d_host_covers_z(self, lib):
        # v5p-4 = 2 chips in a 1x1x2 grid: the z-axis carve-outs must
        # exist and enumeration coords must stay inside the slice grid.
        profs = {p.name: p for p in lib.subslice_profiles(
            EnumerateOptions(mock_topology="v5p-4"))}
        assert profs["1x1x1"].placements == (0, 1)
        assert profs["1x1x2"].placements == (0,)
        h = lib.enumerate(EnumerateOptions(mock_topology="v5p-4"))
        assert [c.ici_coords for c in h.chips] == [(0, 0, 0), (0, 0, 1)]

    def test_v5e_profiles_no_core_level(self, lib):
        profs = {p.name: p for p in lib.subslice_profiles(
            EnumerateOptions(mock_topology="v5e-4"))}
        assert "1c" not in profs
        assert profs["1x1"].chips == 1
        assert profs["2x2"].chips == 4
        assert profs["1x1"].hbm_bytes == 16 << 30


class TestHealth:
    def test_mock_events(self, lib):
        evs = lib.health(EnumerateOptions(
            health_events="chip=1,kind=hbm_uncorrectable|chip=2,kind=thermal"))
        assert len(evs) == 2
        assert evs[0].fatal and evs[0].chip == 1
        assert not evs[1].fatal and evs[1].kind == "thermal"

    def test_no_events(self, lib):
        assert lib.health(EnumerateOptions()) == ()

    def _real_tree(self, tmp_path, chips=4):
        dev = tmp_path / "dev"
        dev.mkdir(exist_ok=True)
        sys = tmp_path / "sys"
        for i in range(chips):
            (dev / f"accel{i}").touch()
            d = sys / "class" / "accel" / f"accel{i}" / "device"
            d.mkdir(parents=True, exist_ok=True)
        return dev, sys

    def test_devfs_healthy_baseline_no_events(self, lib, tmp_path):
        dev, sys = self._real_tree(tmp_path)
        evs = lib.health(EnumerateOptions(
            dev_root=str(dev), sys_root=str(sys), expected_chips="0,1,2,3"))
        assert evs == ()

    def test_devfs_enumeration_diff_chip_lost(self, lib, tmp_path):
        # The GPU-lost analog (device_health.go:281-328): a baseline chip
        # whose devfs entry vanished is fatal chip_lost.
        dev, sys = self._real_tree(tmp_path)
        (dev / "accel2").unlink()
        evs = lib.health(EnumerateOptions(
            dev_root=str(dev), sys_root=str(sys), expected_chips="0,1,2,3"))
        assert [(e.chip, e.kind, e.fatal) for e in evs] == [
            (2, "chip_lost", True)]

    def test_devfs_aer_counters(self, lib, tmp_path):
        dev, sys = self._real_tree(tmp_path)
        base = sys / "class" / "accel"
        (base / "accel1" / "device" / "aer_dev_fatal").write_text(
            "Undefined 0\nTOTAL_ERR_FATAL 2\n")
        (base / "accel3" / "device" / "aer_dev_nonfatal").write_text(
            "RxErr 1\nBadTLP 0\n")
        evs = lib.health(EnumerateOptions(
            dev_root=str(dev), sys_root=str(sys), expected_chips="0,1,2,3"))
        assert [(e.chip, e.kind, e.fatal) for e in evs] == [
            (1, "pcie_aer_fatal", True),
            (3, "pcie_aer_nonfatal", False),
        ]

    def test_aer_pci_address_fallback(self, lib, tmp_path):
        # vfio-bound / TPU-VM hosts may expose the chip with NO accel
        # class node; the counters must then come from the PCI device
        # path (device_health.go:215-328: one pipeline, many sources).
        dev, sys = self._real_tree(tmp_path)
        import shutil as _sh
        _sh.rmtree(sys / "class" / "accel" / "accel1")  # class-less chip
        pci = sys / "bus" / "pci" / "devices" / "0000:00:05.0"
        pci.mkdir(parents=True)
        (pci / "aer_dev_fatal").write_text("TOTAL_ERR_FATAL 1\n")
        evs = lib.health(EnumerateOptions(
            dev_root=str(dev), sys_root=str(sys), expected_chips="0,1,2,3",
            expected_bdfs="0000:00:04.0,0000:00:05.0,0000:00:06.0,"
                          "0000:00:07.0"))
        assert [(e.chip, e.kind, e.fatal) for e in evs] == [
            (1, "pcie_aer_fatal", True)]

    def test_aer_class_path_wins_over_pci_fallback(self, lib, tmp_path):
        # When the accel class node exists, its (empty) counters are
        # authoritative; the PCI path is only consulted when the class
        # attribute is ABSENT.
        dev, sys = self._real_tree(tmp_path)
        (sys / "class" / "accel" / "accel0" / "device"
         / "aer_dev_fatal").write_text("TOTAL_ERR_FATAL 0\n")
        pci = sys / "bus" / "pci" / "devices" / "0000:00:04.0"
        pci.mkdir(parents=True)
        (pci / "aer_dev_fatal").write_text("TOTAL_ERR_FATAL 9\n")
        evs = lib.health(EnumerateOptions(
            dev_root=str(dev), sys_root=str(sys), expected_chips="0",
            expected_bdfs="0000:00:04.0"))
        assert evs == ()

    def test_mock_mode_ignores_expected_chips(self, lib, tmp_path):
        # Mock mode must not consult devfs: no /dev/accel* exists on a
        # dev box, and that must not read as every chip lost.
        evs = lib.health(EnumerateOptions(
            mock_topology="v5e-4", dev_root=str(tmp_path),
            expected_chips="0,1,2,3"))
        assert evs == ()


@pytest.mark.skipif(not NATIVE_AVAILABLE, reason="libtpuinfo.so not built")
class TestBackendParity:
    """Native C++ and Python backends must agree field-for-field."""

    CASES = [
        EnumerateOptions(mock_topology="v5e-4"),
        EnumerateOptions(mock_topology="v5e-8"),
        EnumerateOptions(mock_topology="v5p-8"),
        EnumerateOptions(mock_topology="v5p-16", worker_id=1),
        EnumerateOptions(mock_topology="v5p-32", worker_id=3),
        EnumerateOptions(mock_topology="v4-16"),
        EnumerateOptions(mock_topology="v6e-8"),
        # Unknown type falls back to v5e-4 wholesale on both backends.
        EnumerateOptions(mock_topology="v99-4"),
        # Trailing junk in the suffix is rejected identically.
        EnumerateOptions(mock_topology="v5p-16x"),
        # Partial 3D host (z-extent carve-outs).
        EnumerateOptions(mock_topology="v5p-4"),
    ]

    def test_enumerate_parity(self):
        native, py = NativeTpuLib(), PyTpuLib()
        for opts in self.CASES:
            a = dataclasses.asdict(native.enumerate(opts))
            b = dataclasses.asdict(py.enumerate(opts))
            assert a == b, f"enumerate mismatch for {opts}"

    def test_profiles_parity(self):
        native, py = NativeTpuLib(), PyTpuLib()
        for opts in self.CASES:
            a = [dataclasses.asdict(p) for p in native.subslice_profiles(opts)]
            b = [dataclasses.asdict(p) for p in py.subslice_profiles(opts)]
            assert a == b, f"profiles mismatch for {opts}"

    def test_health_parity(self):
        native, py = NativeTpuLib(), PyTpuLib()
        for events in [
            "chip=0,kind=ici_link_down|chip=3,kind=thermal",
            # Malformed inputs must degrade identically: empty segments,
            # missing '=', non-numeric chip.
            "chip=1,kind=thermal||chip=2,kind=thermal",
            "chip|kind=thermal",
            "chip=x,kind=thermal",
        ]:
            opts = EnumerateOptions(health_events=events)
            assert native.health(opts) == py.health(opts), events

    def test_health_control_file_parity(self, tmp_path):
        """@file form: both backends re-read the control file per call
        (runtime injection seam) and treat a missing file as no
        events."""
        native, py = NativeTpuLib(), PyTpuLib()
        ctl = tmp_path / "health.ctl"
        opts = EnumerateOptions(health_events=f"@{ctl}")
        assert native.health(opts) == py.health(opts) == ()
        # CRLF + leading whitespace: both backends must strip alike.
        ctl.write_text("\n chip=2,kind=hbm_uncorrectable\r\n")
        got = py.health(opts)
        assert got == native.health(opts)
        assert got[0].chip == 2 and got[0].fatal
        ctl.write_text("")  # cleared at runtime
        assert native.health(opts) == py.health(opts) == ()

    def test_devfs_health_parity(self, tmp_path):
        dev = tmp_path / "dev"
        dev.mkdir()
        sys = tmp_path / "sys"
        for i in [0, 1, 3]:  # accel2 lost
            (dev / f"accel{i}").touch()
            d = sys / "class" / "accel" / f"accel{i}" / "device"
            d.mkdir(parents=True)
        (sys / "class" / "accel" / "accel1" / "device"
         / "aer_dev_fatal").write_text("BadTLP 1\nRxErr 2\n")
        opts = EnumerateOptions(dev_root=str(dev), sys_root=str(sys),
                                expected_chips="0,1,2,3")
        native, py = NativeTpuLib(), PyTpuLib()
        assert native.health(opts) == py.health(opts)
        assert any(e.kind == "chip_lost" for e in py.health(opts))

    def test_aer_pci_fallback_parity(self, tmp_path):
        dev = tmp_path / "dev"
        dev.mkdir()
        sys = tmp_path / "sys"
        for i in [0, 1]:
            (dev / f"accel{i}").touch()
        # Only chip 0 has a class node; chip 1 is class-less with AER
        # counters under its PCI address.
        (sys / "class" / "accel" / "accel0" / "device").mkdir(parents=True)
        pci = sys / "bus" / "pci" / "devices" / "0000:00:05.0"
        pci.mkdir(parents=True)
        (pci / "aer_dev_nonfatal").write_text("RxErr 3\n")
        opts = EnumerateOptions(
            dev_root=str(dev), sys_root=str(sys), expected_chips="0,1",
            expected_bdfs="0000:00:04.0,0000:00:05.0")
        native, py = NativeTpuLib(), PyTpuLib()
        assert native.health(opts) == py.health(opts)
        assert [(e.chip, e.kind) for e in py.health(opts)] == [
            (1, "pcie_aer_nonfatal")]

    def test_devfs_junk_entries_parity(self, tmp_path):
        dev = tmp_path / "dev"
        dev.mkdir()
        for name in ["accel0", "accel1", "accel-1", "accel0tmp", "accel", "accel 2"]:
            (dev / name).touch()
        native, py = NativeTpuLib(), PyTpuLib()
        opts = EnumerateOptions(dev_root=str(dev), sys_root=str(tmp_path))
        a = dataclasses.asdict(native.enumerate(opts))
        b = dataclasses.asdict(py.enumerate(opts))
        assert a == b
        assert [c["index"] for c in a["chips"]] == [0, 1]


class TestLoad:
    def test_load_returns_backend(self):
        lib = load()
        h = lib.enumerate(EnumerateOptions(mock_topology="v5e-4"))
        assert h.num_slice_chips == 4

    def test_env_seam(self, monkeypatch):
        monkeypatch.setenv("TPULIB_MOCK_TOPOLOGY", "v5p-16")
        monkeypatch.setenv("TPULIB_MOCK_WORKER_ID", "1")
        opts = EnumerateOptions.from_env()
        assert opts.mock_topology == "v5p-16"
        assert opts.worker_id == 1
