"""Driver + gRPC end-to-end tests: a fake kubelet dials the plugin's unix
sockets, claims flow through the API-server (fake) lookup into
DeviceState, ResourceSlices land in the (fake) API server.

Reference analog: the kubeletplugin helper integration the reference
gets from upstream, plus driver.go's publication/taint logic.
"""

import os
import time

import grpc
import pytest

from k8s_dra_driver_gpu_tpu.kubeletplugin.device_state import Config
from k8s_dra_driver_gpu_tpu.kubeletplugin.driver import Driver
from k8s_dra_driver_gpu_tpu.pkg.dra.proto import dra_plugin_pb2 as drapb
from k8s_dra_driver_gpu_tpu.pkg.dra.proto import plugin_registration_pb2 as regpb
from k8s_dra_driver_gpu_tpu.pkg.dra.service import (
    PluginServer,
    dra_client_stubs,
    registration_client_stubs,
)
from k8s_dra_driver_gpu_tpu.pkg.kubeclient import FakeKubeClient
from tests.fake_kube import make_claim_dict


@pytest.fixture()
def kube():
    return FakeKubeClient()


@pytest.fixture()
def driver(tmp_root, kube):
    d = Driver(
        Config.mock(root=tmp_root, topology="v5e-4"),
        kube,
        node_name="node-a",
        enable_health_monitor=False,
    )
    d.publish_resources()
    return d


def put_claim(kube, uid, devices, **kw):
    obj = make_claim_dict(uid, devices, **kw)
    kube.create("resource.k8s.io", "v1", "resourceclaims", obj,
                namespace=obj["metadata"]["namespace"])
    return obj


class TestResourceSlices:
    def test_combined_slice_published(self, driver, kube):
        slices = kube.list("resource.k8s.io", "v1", "resourceslices")
        assert len(slices) == 1
        spec = slices[0]["spec"]
        assert spec["driver"] == "tpu.dra.dev"
        assert spec["nodeName"] == "node-a"
        names = [d["name"] for d in spec["devices"]]
        assert "chip-0" in names
        # Sub-slice carve-outs publish alongside chips in combined mode.
        assert any(n.startswith("ss-") or "-ss-" in n for n in names)
        # Shared counters guard core-level overcommit.
        counters = spec["sharedCounters"][0]["counters"]
        assert "core-0-0" in counters
        assert "hbm-0" in counters
        chip0 = next(d for d in spec["devices"] if d["name"] == "chip-0")
        assert chip0["consumesCounters"][0]["counters"]["core-0-0"] == {
            "value": "1"
        }

    def test_split_slices_on_new_server(self, tmp_root, kube):
        kube.version = {"major": "1", "minor": "35"}
        d = Driver(
            Config.mock(root=os.path.join(tmp_root, "s"), topology="v5e-4"),
            kube, node_name="node-b", enable_health_monitor=False,
        )
        d.publish_resources()
        slices = kube.list("resource.k8s.io", "v1", "resourceslices")
        assert len(slices) == 2
        names = {s["metadata"]["name"] for s in slices}
        assert any("chips" in n for n in names)
        assert any("partitions" in n for n in names)

    def test_republish_unchanged_is_write_free(self, driver, kube):
        # Content-hash diff: re-publishing an unchanged node costs
        # zero kube writes and the generation does not move (the real
        # DRA plugin treats generation bumps as inventory churn).
        stats = driver.publish_resources()
        assert stats["writes"] == 0 and stats["skipped"] >= 1
        s = kube.list("resource.k8s.io", "v1", "resourceslices")[0]
        assert s["spec"]["pool"]["generation"] == 1

    def test_split_mode_without_partitions_publishes_complete_pool(
        self, tmp_root, kube
    ):
        # Default gates (no DynamicSubSlice/Passthrough): split mode has
        # no partition devices, so exactly ONE slice must be published
        # and resourceSliceCount must say 1 -- schedulers ignore pools
        # whose slice count doesn't match what's visible.
        kube.version = {"major": "1", "minor": "35"}
        d = Driver(
            Config.mock(root=os.path.join(tmp_root, "np"), topology="v5e-4",
                        gates=""),
            kube, node_name="node-c", enable_health_monitor=False,
        )
        assert d.publication_mode == "split"
        d.publish_resources()
        slices = kube.list("resource.k8s.io", "v1", "resourceslices")
        assert len(slices) == 1
        assert slices[0]["spec"]["pool"]["resourceSliceCount"] == 1

    def test_split_slice_counts_and_shared_generation(self, tmp_root, kube):
        kube.version = {"major": "1", "minor": "35"}
        d = Driver(
            Config.mock(root=os.path.join(tmp_root, "sg"), topology="v5e-4"),
            kube, node_name="node-b", enable_health_monitor=False,
        )
        d.publish_resources()
        d.publish_resources()
        slices = kube.list("resource.k8s.io", "v1", "resourceslices")
        assert len(slices) == 2
        assert all(s["spec"]["pool"]["resourceSliceCount"] == 2
                   for s in slices)
        gens = {s["spec"]["pool"]["generation"] for s in slices}
        assert len(gens) == 1  # one shared pool generation per publish

    def test_mode_transition_deletes_stale_combined_slice(
        self, tmp_root, kube
    ):
        root = os.path.join(tmp_root, "tr")
        d1 = Driver(
            Config.mock(root=root, topology="v5e-4"),
            kube, node_name="node-b", enable_health_monitor=False,
            publication_mode="combined",
        )
        d1.publish_resources()
        d1.publish_resources()  # no-op: combined slice stays at gen 1
        d2 = Driver(
            Config.mock(root=root, topology="v5e-4"),
            kube, node_name="node-b", enable_health_monitor=False,
            publication_mode="split",
        )
        d2.publish_resources()
        slices = kube.list("resource.k8s.io", "v1", "resourceslices")
        names = {s["metadata"]["name"] for s in slices}
        assert len(slices) == 2
        assert all("chips" in n or "partitions" in n for n in names)
        # The new slices outrank the deleted combined slice's generation.
        assert all(s["spec"]["pool"]["generation"] == 2 for s in slices)

    def test_legacy_mode_publishes_whole_chips_only(self, tmp_root, kube):
        d = Driver(
            Config.mock(root=os.path.join(tmp_root, "lg"), topology="v5e-4"),
            kube, node_name="node-d", enable_health_monitor=False,
            publication_mode="legacy",
        )
        d.publish_resources()
        slices = kube.list("resource.k8s.io", "v1", "resourceslices")
        assert len(slices) == 1
        spec = slices[0]["spec"]
        assert "sharedCounters" not in spec
        names = [dev["name"] for dev in spec["devices"]]
        assert names and all(n.startswith("chip-") for n in names)
        assert all("consumesCounters" not in dev for dev in spec["devices"])

    def test_legacy_mode_keeps_passthrough_devices(self, tmp_root, kube):
        # Whole-chip passthrough needs no shared counters, so pre-1.35
        # servers must not lose it; only partition devices are withheld.
        from k8s_dra_driver_gpu_tpu.pkg.featuregates import FeatureGates
        from k8s_dra_driver_gpu_tpu.tpulib.binding import (
            EnumerateOptions,
            PyTpuLib,
        )
        from tests.test_vfio_health import fake_pci_tree

        bdfs = [
            c.pci_bdf
            for c in PyTpuLib().enumerate(
                EnumerateOptions(mock_topology="v5e-4")).chips
        ]
        import pathlib
        sys_root = fake_pci_tree(pathlib.Path(tmp_root), bdfs)
        d = Driver(
            Config(
                root=os.path.join(tmp_root, "lp"),
                tpulib_opts=EnumerateOptions(
                    mock_topology="v5e-4", sys_root=sys_root,
                    dev_root=os.path.join(tmp_root, "dev"),
                ),
                feature_gates=FeatureGates.parse("PassthroughSupport=true"),
                cdi_root=os.path.join(tmp_root, "cdi"),
                tenancy_agents=False,
            ),
            kube, node_name="node-e", enable_health_monitor=False,
            publication_mode="legacy",
        )
        d.publish_resources()
        slices = kube.list("resource.k8s.io", "v1", "resourceslices")
        names = [dev["name"] for s in slices for dev in s["spec"]["devices"]]
        assert any(n.endswith("-passthrough") for n in names)
        assert not any("-ss-" in n or n.startswith("ss-") for n in names)


class TestPrepareFlow:
    def test_prepare_via_api_lookup(self, driver, kube):
        put_claim(kube, "u1", ["chip-0", "chip-1"], namespace="team-a")
        out = driver.prepare_resource_claims(
            [{"uid": "u1", "namespace": "team-a", "name": "u1"}]
        )
        devices, err = out["u1"]
        assert err == ""
        assert {d["device_name"] for d in devices} == {"chip-0", "chip-1"}
        assert all(d["pool_name"] == "node-a" for d in devices)
        assert all(d["cdi_device_ids"] for d in devices)

    def test_uid_mismatch_rejected(self, driver, kube):
        put_claim(kube, "u1", ["chip-0"])
        out = driver.prepare_resource_claims(
            [{"uid": "other-uid", "namespace": "default", "name": "u1"}]
        )
        devices, err = out["other-uid"]
        assert devices == [] and "UID mismatch" in err

    def test_unprepare(self, driver, kube):
        put_claim(kube, "u1", ["chip-0"])
        driver.prepare_resource_claims(
            [{"uid": "u1", "namespace": "default", "name": "u1"}]
        )
        out = driver.unprepare_resource_claims([{"uid": "u1"}])
        assert out == {"u1": ""}
        assert driver.state.prepared_claims() == {}

    def test_multi_claim_fanout_prepares_all(self, driver, kube,
                                             monkeypatch):
        """A multi-claim NodePrepareResources fans out to the thread
        pool: all claims land, per-claim errors stay isolated, and the
        stalled middle of one claim doesn't serialize the others (wall
        ~max, not sum, of the per-claim stalls)."""
        refs = []
        for i in range(3):
            put_claim(kube, f"fan-{i}", [f"chip-{i}"])
            refs.append({"uid": f"fan-{i}", "namespace": "default",
                         "name": f"fan-{i}"})
        refs.append({"uid": "fan-bad", "namespace": "default",
                     "name": "missing"})
        monkeypatch.setenv("TPU_DRA_STALL_AT_SEGMENT", "prep_devices")
        monkeypatch.setenv("TPU_DRA_STALL_SECONDS", "1.2")
        t0 = time.monotonic()
        out = driver.prepare_resource_claims(refs)
        wall = time.monotonic() - t0
        for i in range(3):
            devices, err = out[f"fan-{i}"]
            assert err == ""
            assert [d["device_name"] for d in devices] == [f"chip-{i}"]
        devices, err = out["fan-bad"]
        assert devices == [] and err != ""
        # Serialized would be >= 3.6s of stalls alone; the generous
        # margin absorbs the multi-second fsync hiccups BASELINE.md
        # documents for CI boxes.
        assert wall < 3.0, f"fan-out serialized: {wall:.2f}s"


class TestHealthTaints:
    def test_real_devfs_chip_lost_taints_and_republish(
        self, tmp_path, kube
    ):
        # End-to-end real-source path: enumerate a devfs tree, then make
        # a chip's devfs entry vanish -- the monitor (primed with the
        # startup baseline) must emit chip_lost and the republished
        # slice must carry the NoExecute taint.
        from k8s_dra_driver_gpu_tpu.tpulib.binding import EnumerateOptions

        dev = tmp_path / "dev"
        dev.mkdir()
        sys_root = tmp_path / "sys"
        for i in range(4):
            (dev / f"accel{i}").touch()
            (sys_root / "class" / "accel" / f"accel{i}"
             / "device").mkdir(parents=True)
        from k8s_dra_driver_gpu_tpu.pkg.featuregates import FeatureGates

        cfg = Config(
            root=str(tmp_path / "state"),
            tpulib_opts=EnumerateOptions(
                dev_root=str(dev), sys_root=str(sys_root)),
            feature_gates=FeatureGates(),
            cdi_root=str(tmp_path / "cdi"),
        )
        d = Driver(cfg, kube, node_name="node-a",
                   enable_health_monitor=True)
        assert d.health_monitor._opts.expected_chips == "0,1,2,3"
        d.publish_resources()
        assert d.health_monitor.poll_once() == []

        (dev / "accel1").unlink()
        taints = d.health_monitor.poll_once()
        d._on_health_taints(taints)
        s = kube.list("resource.k8s.io", "v1", "resourceslices")[0]
        chip1 = next(dev_ for dev_ in s["spec"]["devices"]
                     if dev_["name"] == "chip-1")
        assert chip1["taints"] == [{
            "key": "tpu.dra.dev/chip_lost", "value": "true",
            "effect": "NoExecute",
        }]
        d.stop()

    def test_taints_republish(self, tmp_root, kube):
        from k8s_dra_driver_gpu_tpu.tpulib.binding import EnumerateOptions

        cfg = Config.mock(root=tmp_root, topology="v5e-4")
        d = Driver(cfg, kube, node_name="node-a", enable_health_monitor=False)
        d.publish_resources()
        # Simulate a fatal event on chip 1 through the monitor mapping.
        from k8s_dra_driver_gpu_tpu.kubeletplugin.health import (
            ChipHealthMonitor,
        )
        mon = ChipHealthMonitor(
            d.state._tpulib,
            EnumerateOptions(
                mock_topology="v5e-4",
                health_events="chip=1,kind=ici_link_down|chip=2,kind=thermal",
            ),
            d._on_health_taints,
        )
        taints = mon.poll_once()
        d._on_health_taints(taints)
        s = kube.list("resource.k8s.io", "v1", "resourceslices")[0]
        devs = {x["name"]: x for x in s["spec"]["devices"]}
        assert devs["chip-1"]["taints"][0]["key"] == "tpu.dra.dev/ici_link_down"
        assert devs["chip-1"]["taints"][0]["effect"] == "NoExecute"
        # Non-fatal: observe-only taint (no effect key).
        assert "effect" not in devs["chip-2"]["taints"][0]
        assert "taints" not in devs["chip-0"]

    def test_unmonitored_taint_when_health_disabled(self, tmp_root, kube):
        # Reference taints gpu.nvidia.com/unmonitored (Effect=None) when
        # the health monitor is off.
        d = Driver(
            Config.mock(root=os.path.join(tmp_root, "um"), topology="v5e-4"),
            kube, node_name="node-um", enable_health_monitor=False,
        )
        d.publish_resources()
        s = next(x for x in kube.list("resource.k8s.io", "v1",
                                      "resourceslices")
                 if x["spec"]["nodeName"] == "node-um")
        devs = {x["name"]: x for x in s["spec"]["devices"]}
        taint = devs["chip-0"]["taints"][0]
        assert taint["key"] == "tpu.dra.dev/unmonitored"
        assert "effect" not in taint  # observe-only

    def test_ignored_kinds(self):
        from k8s_dra_driver_gpu_tpu.kubeletplugin.health import (
            health_event_to_taints,
        )
        from k8s_dra_driver_gpu_tpu.tpulib.binding import HealthEvent

        assert health_event_to_taints(
            HealthEvent(chip=0, kind="thermal_notice", fatal=False)
        ) == []

    def test_unchanged_taint_republish_is_zero_kube_calls(self,
                                                          tmp_root):
        """ISSUE 5 satellite regression: the health monitor reports the
        FULL taint list every poll, so a steady (even non-empty) taint
        set arrives unchanged once per interval -- the republish must
        short-circuit on the content hash and touch the apiserver ZERO
        times. A real taint change still publishes (one write, no
        pool-generation bump: taints are not inventory churn)."""
        from k8s_dra_driver_gpu_tpu.kubeletplugin.health import (
            DeviceTaint,
        )
        from tests.fake_kube import CountingKube

        fake = FakeKubeClient()
        counting = CountingKube(fake)
        d = Driver(
            Config.mock(root=os.path.join(tmp_root, "zh"),
                        topology="v5e-4"),
            counting, node_name="node-zh", enable_health_monitor=False,
        )
        d.publish_resources()
        taints = [DeviceTaint(device="chip-2",
                              key="tpu.dra.dev/thermal",
                              value="true", effect="")]
        d._on_health_taints(taints)  # taint appears: one write...
        s = fake.list("resource.k8s.io", "v1", "resourceslices")[0]
        assert s["spec"]["pool"]["generation"] == 1  # ...but no bump
        writes0, reads0 = counting.writes, counting.reads

        def skip_count():
            metric = next(iter(
                d.metrics.slice_publish_skipped.collect()))
            return next(s.value for s in metric.samples
                        if s.name.endswith("_total"))

        skipped0 = skip_count()
        for _ in range(5):  # five no-op health polls
            d._on_health_taints(taints)
        assert counting.writes == writes0, \
            "unchanged taint set must republish with zero kube writes"
        assert counting.reads == reads0, \
            "the hash short-circuit must not even list live slices"
        assert skip_count() > skipped0
        # The taint CLEARING is a real change again: exactly one slice
        # write, still no generation bump.
        d._on_health_taints([])
        assert counting.writes == writes0 + 1
        s = fake.list("resource.k8s.io", "v1", "resourceslices")[0]
        assert s["spec"]["pool"]["generation"] == 1
        assert all("taints" not in dev or not any(
            t.get("key") == "tpu.dra.dev/thermal"
            for t in dev["taints"])
            for dev in s["spec"]["devices"])

    def test_publish_recheck_repairs_external_slice_deletion(
            self, tmp_root, monkeypatch):
        """The hash memo must not mask external drift forever: past
        TPU_DRA_PUBLISH_RECHECK_S the health republish goes through the
        live diff (one list read, zero writes when converged) and
        recreates a slice some other actor deleted."""
        monkeypatch.setenv("TPU_DRA_PUBLISH_RECHECK_S", "0")
        fake = FakeKubeClient()
        d = Driver(
            Config.mock(root=os.path.join(tmp_root, "rh"),
                        topology="v5e-4"),
            fake, node_name="node-rh", enable_health_monitor=False,
        )
        d.publish_resources()
        name = fake.list("resource.k8s.io", "v1",
                         "resourceslices")[0]["metadata"]["name"]
        fake.delete("resource.k8s.io", "v1", "resourceslices", name)
        assert fake.list("resource.k8s.io", "v1", "resourceslices") == []
        d._on_health_taints([])  # unchanged taints, but the recheck is due
        restored = fake.list("resource.k8s.io", "v1", "resourceslices")
        assert [s["metadata"]["name"] for s in restored] == [name]


class TestCleanup:
    def test_stale_claim_reaped(self, driver, kube):
        put_claim(kube, "u1", ["chip-0"])
        driver.prepare_resource_claims(
            [{"uid": "u1", "namespace": "default", "name": "u1"}]
        )
        # Claim deleted from the API server behind our back.
        kube.delete("resource.k8s.io", "v1", "resourceclaims", "u1",
                    namespace="default")
        removed = driver.cleanup.cleanup_once()
        assert removed == ["u1"]
        assert driver.state.prepared_claims() == {}

    def test_live_claim_kept(self, driver, kube):
        put_claim(kube, "u1", ["chip-0"])
        driver.prepare_resource_claims(
            [{"uid": "u1", "namespace": "default", "name": "u1"}]
        )
        assert driver.cleanup.cleanup_once() == []
        assert "u1" in driver.state.prepared_claims()

    def test_recreated_claim_uid_mismatch_reaped(self, driver, kube):
        put_claim(kube, "u1", ["chip-0"])
        driver.prepare_resource_claims(
            [{"uid": "u1", "namespace": "default", "name": "u1"}]
        )
        kube.delete("resource.k8s.io", "v1", "resourceclaims", "u1",
                    namespace="default")
        put_claim(kube, "u1-reborn", ["chip-1"], name="u1")
        assert driver.cleanup.cleanup_once() == ["u1"]


class TestGRPCEndToEnd:
    def test_kubelet_dialog(self, tmp_root, kube):
        driver = Driver(
            Config.mock(root=os.path.join(tmp_root, "st"), topology="v5e-4"),
            kube, node_name="node-a", enable_health_monitor=False,
        )
        put_claim(kube, "u1", ["chip-0"], namespace="ns1")
        server = PluginServer(
            "tpu.dra.dev",
            plugin_dir=os.path.join(tmp_root, "plugin"),
            registry_dir=os.path.join(tmp_root, "registry"),
            prepare_fn=driver.prepare_resource_claims,
            unprepare_fn=driver.unprepare_resource_claims,
        )
        server.start()
        try:
            # Kubelet leg 1: registration handshake.
            ch, get_info, notify = registration_client_stubs(
                server.registry_socket
            )
            info = get_info(regpb.InfoRequest(), timeout=5)
            assert info.type == "DRAPlugin"
            assert info.name == "tpu.dra.dev"
            assert info.endpoint == server.plugin_socket
            notify(regpb.RegistrationStatus(plugin_registered=True), timeout=5)
            assert server.registration.registered
            ch.close()

            # Kubelet leg 2: prepare/unprepare over the plugin socket.
            ch2, prepare, unprepare = dra_client_stubs(server.plugin_socket)
            req = drapb.NodePrepareResourcesRequest()
            c = req.claims.add()
            c.uid, c.namespace, c.name = "u1", "ns1", "u1"
            resp = prepare(req, timeout=10)
            assert resp.claims["u1"].error == ""
            assert resp.claims["u1"].devices[0].device_name == "chip-0"
            assert resp.claims["u1"].devices[0].cdi_device_ids[0].startswith(
                "k8s.tpu.dra.dev/claim="
            )
            # Unknown claim: error in-band, not a transport failure.
            req2 = drapb.NodeUnprepareResourcesRequest()
            c2 = req2.claims.add()
            c2.uid = "u1"
            resp2 = unprepare(req2, timeout=10)
            assert resp2.claims["u1"].error == ""
            ch2.close()
        finally:
            server.stop()

    def test_version_negotiation_v1_and_v1beta1(self, tmp_root, kube):
        """A kubelet speaking EITHER advertised service prepares a claim
        on the same socket (ref draplugin.go:757-801)."""
        from k8s_dra_driver_gpu_tpu.pkg.dra.proto import (
            dra_plugin_v1_pb2 as v1pb,
        )
        from k8s_dra_driver_gpu_tpu.pkg.dra.service import (
            DRA_SERVICE_V1,
            DRA_SERVICE_V1BETA1,
            SUPPORTED_SERVICES,
        )

        driver = Driver(
            Config.mock(root=os.path.join(tmp_root, "st"), topology="v5e-4"),
            kube, node_name="node-a", enable_health_monitor=False,
        )
        put_claim(kube, "u1", ["chip-0"], namespace="ns1")
        put_claim(kube, "u2", ["chip-1"], namespace="ns1")
        server = PluginServer(
            "tpu.dra.dev",
            plugin_dir=os.path.join(tmp_root, "plugin"),
            registry_dir=os.path.join(tmp_root, "registry"),
            prepare_fn=driver.prepare_resource_claims,
            unprepare_fn=driver.unprepare_resource_claims,
        )
        server.start()
        try:
            # Registration advertises full service names, v1 preferred.
            ch, get_info, _ = registration_client_stubs(
                server.registry_socket)
            info = get_info(regpb.InfoRequest(), timeout=5)
            assert list(info.supported_versions) == SUPPORTED_SERVICES
            assert list(info.supported_versions) == [
                "v1.DRAPlugin", "v1beta1.DRAPlugin"]
            ch.close()

            # v1 kubelet.
            ch1, prepare1, unprepare1 = dra_client_stubs(
                server.plugin_socket, service=DRA_SERVICE_V1)
            req = v1pb.NodePrepareResourcesRequest()
            c = req.claims.add()
            c.uid, c.namespace, c.name = "u1", "ns1", "u1"
            resp = prepare1(req, timeout=10)
            assert resp.claims["u1"].error == ""
            assert resp.claims["u1"].devices[0].device_name == "chip-0"
            unreq = v1pb.NodeUnprepareResourcesRequest()
            unreq.claims.add().uid = "u1"
            assert unprepare1(unreq, timeout=10).claims["u1"].error == ""
            ch1.close()

            # v1beta1 kubelet against the SAME socket.
            ch2, prepare2, _ = dra_client_stubs(
                server.plugin_socket, service=DRA_SERVICE_V1BETA1)
            req2 = drapb.NodePrepareResourcesRequest()
            c2 = req2.claims.add()
            c2.uid, c2.namespace, c2.name = "u2", "ns1", "u2"
            resp2 = prepare2(req2, timeout=10)
            assert resp2.claims["u2"].error == ""
            assert resp2.claims["u2"].devices[0].device_name == "chip-1"
            ch2.close()
        finally:
            server.stop()
