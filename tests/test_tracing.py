"""Claim-lifecycle tracing, flight recorder, and log correlation.

Covers pkg/tracing.py (W3C traceparent contexts, with-guarded spans,
sampling, the bounded exporter + debug endpoints), pkg/flightrecorder,
the SegmentTimer span integration (pkg/timing.py), the logsetup trace
filter -- and the acceptance end-to-end: a claim allocated by the REAL
scheduler and prepared by a REAL DeviceState yields ONE trace id whose
span tree contains the scheduler's commit span and the plugin's
prepare-segment child spans, retrievable over HTTP from
/debug/traces on the metrics listener.
"""

import json
import logging
import os
import threading
import urllib.request

import pytest

from k8s_dra_driver_gpu_tpu.kubeletplugin.claim import ResourceClaim
from k8s_dra_driver_gpu_tpu.kubeletplugin.device_state import (
    Config,
    DeviceState,
)
from k8s_dra_driver_gpu_tpu.pkg import flightrecorder, logsetup, tracing
from k8s_dra_driver_gpu_tpu.pkg.kubeclient import FakeKubeClient
from k8s_dra_driver_gpu_tpu.pkg.metrics import (
    MetricsServer,
    SchedulerMetrics,
)
from k8s_dra_driver_gpu_tpu.pkg.scheduler import DraScheduler
from k8s_dra_driver_gpu_tpu.pkg.sliceutil import publish_resource_slices
from k8s_dra_driver_gpu_tpu.pkg.timing import SegmentTimer

RES = ("resource.k8s.io", "v1")


@pytest.fixture(autouse=True)
def fresh_tracing(monkeypatch):
    """Full sampling + a private exporter/recorder per test."""
    monkeypatch.setenv(tracing.ENV_SAMPLE, "1")
    exporter = tracing.set_exporter(tracing.TraceExporter())
    recorder = flightrecorder.set_default(flightrecorder.FlightRecorder())
    yield exporter, recorder
    tracing.set_exporter(tracing.TraceExporter())
    flightrecorder.set_default(flightrecorder.FlightRecorder())


class TestSpanContext:
    def test_traceparent_roundtrip(self):
        ctx = tracing.SpanContext(trace_id="ab" * 16, span_id="cd" * 8)
        parsed = tracing.SpanContext.from_traceparent(
            ctx.to_traceparent())
        assert parsed == ctx
        assert parsed.sampled

    def test_unsampled_flag_roundtrip(self):
        ctx = tracing.SpanContext(trace_id="ab" * 16, span_id="cd" * 8,
                                  sampled=False)
        header = ctx.to_traceparent()
        assert header.endswith("-00")
        assert not tracing.SpanContext.from_traceparent(header).sampled

    @pytest.mark.parametrize("bad", [
        "", "garbage", "00-short-cdcdcdcdcdcdcdcd-01",
        "00-" + "g" * 32 + "-" + "cd" * 8 + "-01",  # non-hex
        "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",  # zero trace id
        "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # zero span id
        None, 7,
    ])
    def test_malformed_rejected(self, bad):
        assert tracing.SpanContext.from_traceparent(bad) is None

    def test_extract_from_annotations(self):
        ctx = tracing.SpanContext(trace_id="ab" * 16, span_id="cd" * 8)
        ann = tracing.inject(ctx, {})
        assert tracing.extract(ann) == ctx
        assert tracing.extract({}) is None
        assert tracing.extract(None) is None
        assert tracing.trace_id_of(ann) == "ab" * 16


class TestSpans:
    def test_nesting_and_parenting(self, fresh_tracing):
        exporter, _ = fresh_tracing
        with tracing.span("outer") as outer:
            assert tracing.current_span() is outer
            with tracing.span("inner") as inner:
                assert inner.context.trace_id == outer.context.trace_id
                assert inner.parent_id == outer.context.span_id
        assert tracing.current_span() is None
        names = {d["name"] for d in exporter.spans()}
        assert names == {"outer", "inner"}

    def test_error_recorded_and_stack_unwound(self, fresh_tracing):
        exporter, _ = fresh_tracing
        with pytest.raises(ValueError):
            with tracing.span("boom"):
                raise ValueError("nope")
        assert tracing.current_span() is None
        [doc] = exporter.spans()
        assert "ValueError" in doc["error"]

    def test_remote_parent(self, fresh_tracing):
        exporter, _ = fresh_tracing
        remote = tracing.SpanContext(trace_id="ab" * 16,
                                     span_id="cd" * 8)
        with tracing.span("child", parent=remote) as sp:
            assert sp.context.trace_id == remote.trace_id
            assert sp.parent_id == remote.span_id

    def test_sampling_off_is_noop(self, fresh_tracing, monkeypatch):
        exporter, _ = fresh_tracing
        monkeypatch.setenv(tracing.ENV_SAMPLE, "0")
        with tracing.span("root") as sp:
            assert not sp.recording
            with tracing.span("child") as child:
                assert not child.recording
        assert exporter.spans() == []

    def test_unsampled_root_decision_inherited(self, fresh_tracing,
                                               monkeypatch):
        """At fractional rates the root's NO must be inherited: the
        unsampled root still occupies the thread stack, so a nested
        span sees it as parent instead of re-rolling an independent
        root decision (which would export orphan parentless traces)."""
        exporter, _ = fresh_tracing
        monkeypatch.setenv(tracing.ENV_SAMPLE, "0.5")
        # First roll (the root) lands unsampled; any illegitimate
        # re-roll by a nested span WOULD land sampled.
        rolls = iter([0.9, 0.0, 0.0, 0.0])
        monkeypatch.setattr(tracing.random, "random",
                            lambda: next(rolls))
        with tracing.span("root") as root:
            assert not root.recording
            assert tracing.current_span() is root
            with tracing.span("child") as child:
                assert not child.recording
        assert tracing.current_span() is None
        assert exporter.spans() == []

    def test_unsampled_remote_parent_is_noop(self, fresh_tracing):
        exporter, _ = fresh_tracing
        remote = tracing.SpanContext(trace_id="ab" * 16,
                                     span_id="cd" * 8, sampled=False)
        with tracing.span("child", parent=remote) as sp:
            assert not sp.recording
        assert exporter.spans() == []

    def test_threads_have_independent_stacks(self, fresh_tracing):
        seen = {}

        def worker():
            seen["in_thread"] = tracing.current_span()

        with tracing.span("main-only"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["in_thread"] is None


class TestExporter:
    def test_ring_is_bounded(self):
        exp = tracing.TraceExporter(max_spans=16)
        for i in range(100):
            with tracing.span(f"s{i}"):
                pass
            exp.export(tracing.start_span("x"))
        assert len(exp.spans()) <= 16

    def test_traces_grouped_and_sorted(self, fresh_tracing):
        exporter, _ = fresh_tracing
        with tracing.span("a") as a:
            with tracing.span("b"):
                pass
        traces = exporter.traces()
        spans = traces[a.context.trace_id]
        assert [s["name"] for s in spans] == ["a", "b"] or \
            [s["name"] for s in spans] == ["b", "a"]

    def test_jsonl_file_sink(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        exp = tracing.set_exporter(tracing.TraceExporter(path=path))
        with tracing.span("filed"):
            pass
        lines = [json.loads(line) for line in
                 open(path, encoding="utf-8")]
        assert lines and lines[0]["name"] == "filed"
        assert exp.exported_total == 1

    def test_jsonl_sink_rotates_at_size_cap(self, tmp_path):
        """TPU_DRA_TRACE_FILE_MAX_MB rotation: at the size cap the
        live file shifts to .1 (then .2 ... up to keep-N, oldest
        dropped), bounding total disk for a long-lived sampled
        binary."""
        path = str(tmp_path / "trace.jsonl")
        tracing.set_exporter(tracing.TraceExporter(
            path=path, max_file_bytes=2000, keep_files=3))
        for i in range(200):
            with tracing.span(f"rot-{i}"):
                pass
        files = sorted(os.listdir(tmp_path))
        assert files == ["trace.jsonl", "trace.jsonl.1",
                         "trace.jsonl.2", "trace.jsonl.3"]
        # keep-N bound: nothing past .3, rotated files near the cap.
        assert os.path.getsize(tmp_path / "trace.jsonl.1") >= 2000
        # Every rotated file still holds valid JSONL.
        for name in files:
            for line in open(tmp_path / name, encoding="utf-8"):
                json.loads(line)

    def test_rotation_picks_up_existing_file_size(self, tmp_path):
        """A restart resumes the size accounting from the on-disk
        file instead of starting at zero (the cap holds across
        restarts)."""
        path = str(tmp_path / "trace.jsonl")
        with open(path, "w", encoding="utf-8") as f:
            f.write("x" * 3000 + "\n")
        tracing.set_exporter(tracing.TraceExporter(
            path=path, max_file_bytes=2000, keep_files=2))
        with tracing.span("after-restart"):
            pass
        assert os.path.exists(path + ".1")  # rotated immediately

    def test_rotation_env_knobs(self, tmp_path, monkeypatch):
        monkeypatch.setenv(tracing.ENV_TRACE_FILE_MAX_MB, "0.001")
        monkeypatch.setenv(tracing.ENV_TRACE_FILE_KEEP, "2")
        exp = tracing.TraceExporter(path=str(tmp_path / "t.jsonl"))
        assert exp._max_file_bytes == int(0.001 * 1024 * 1024)
        assert exp._keep_files == 2

    def test_unwritable_sink_disables_never_raises(self, tmp_path):
        exp = tracing.set_exporter(tracing.TraceExporter(
            path=str(tmp_path / "no-such-dir" / "t.jsonl")))
        with tracing.span("survives"):
            pass  # write error logged, op unaffected
        assert exp._file_broken
        assert len(exp.spans()) == 1  # ring still records


class TestSegmentTimerTracing:
    def test_segments_are_child_spans_of_remote_parent(
            self, fresh_tracing):
        exporter, _ = fresh_tracing
        remote = tracing.SpanContext(trace_id="ab" * 16,
                                     span_id="cd" * 8)
        timer = SegmentTimer("prepare", "uid-1", parent=remote)
        with timer.segment("step_one"):
            pass
        timer.done()
        by_name = {d["name"]: d for d in exporter.spans()}
        assert by_name["prepare"]["parent_id"] == remote.span_id
        assert by_name["step_one"]["parent_id"] == \
            by_name["prepare"]["span_id"]
        assert by_name["step_one"]["trace_id"] == remote.trace_id
        assert timer.trace_id == remote.trace_id
        # Segment wall-times still collected exactly as before.
        assert "step_one" in timer.segments
        assert "t_step_one_ms" in by_name["prepare"]["attrs"]

    def test_fault_seam_behavior_preserved(self, fresh_tracing):
        """The pkg/faults segment seam still fires at segment START --
        before the segment's span is entered, so a crash-at-segment
        never exports a half-open segment span."""
        from k8s_dra_driver_gpu_tpu.pkg import faults

        exporter, _ = fresh_tracing
        faults.arm("segment:seamcheck", mode="error")
        try:
            timer = SegmentTimer("prepare", "uid-2")
            with pytest.raises(faults.InjectedFault):
                with timer.segment("seamcheck"):
                    raise AssertionError("segment body must not run")
        finally:
            faults.reset()
        assert "seamcheck" not in {d["name"] for d in exporter.spans()}


class TestFlightRecorder:
    def test_record_and_query_by_key_or_alias(self, fresh_tracing):
        _, rec = fresh_tracing
        rec.record("uid-1", "fit", alias="default/c1", outcome="unfit")
        rec.record("default/c1", "enqueue")
        by_uid = rec.events("uid-1")
        by_name = rec.events("default/c1")
        # Identity closure over the alias: BOTH spellings return the
        # full story -- the uid query also pulls the alias-less
        # enqueue recorded under ns/name before the uid existed,
        # because the aliased fit event ties the two identities.
        assert {e["event"] for e in by_uid} == {"fit", "enqueue"}
        assert {e["event"] for e in by_name} == {"fit", "enqueue"}
        # An unrelated claim's events stay out of both views.
        rec.record("uid-2", "fit", alias="default/other")
        assert {e["event"] for e in rec.events("uid-1")} == \
            {"fit", "enqueue"}

    def test_ring_bounded(self):
        rec = flightrecorder.FlightRecorder(capacity=32)
        for i in range(500):
            rec.record("k", f"e{i}")
        assert len(rec.events("k")) <= 32
        assert rec.recorded_total == 500

    def test_dump_readable(self, fresh_tracing):
        _, rec = fresh_tracing
        rec.record("uid-9", "eviction", state="EvictionPlanned")
        dump = rec.dump("uid-9")
        assert "eviction" in dump and "EvictionPlanned" in dump
        assert "no flight-recorder events" in rec.dump("unknown")


class TestLogCorrelation:
    def test_filter_injects_trace_id(self, fresh_tracing):
        filt = logsetup.TraceContextFilter()
        record = logging.LogRecord("t", logging.INFO, __file__, 1,
                                   "msg", (), None)
        with tracing.span("op", attrs={"claim_uid": "uid-7"}):
            assert filt.filter(record)
            assert record.trace_id
            assert record.claim_uid == "uid-7"
        record2 = logging.LogRecord("t", logging.INFO, __file__, 1,
                                    "msg", (), None)
        filt.filter(record2)
        assert record2.trace_id == ""
        # FORMAT renders with the injected fields.
        out = logging.Formatter(logsetup.FORMAT).format(record)
        assert record.trace_id in out


def _http_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
        return json.loads(resp.read())


class TestEndToEndTrace:
    """The acceptance criterion: one trace, scheduler commit span ->
    plugin prepare-segment child spans, via the traceparent annotation
    stamped on the claim -- served at /debug/traces."""

    def _cluster(self, node: str = "node-0"):
        fake = FakeKubeClient()
        fake.create(*RES, "deviceclasses", {
            "apiVersion": "resource.k8s.io/v1", "kind": "DeviceClass",
            "metadata": {"name": "tpu.dra.dev"},
            "spec": {"selectors": [{"cel": {
                "expression": 'device.driver == "tpu.dra.dev"'}}]},
        })
        publish_resource_slices(fake, [{
            "apiVersion": "resource.k8s.io/v1", "kind": "ResourceSlice",
            "metadata": {"name": f"{node}-tpu.dra.dev"},
            "spec": {
                "driver": "tpu.dra.dev", "nodeName": node,
                "pool": {"name": node, "generation": 1,
                         "resourceSliceCount": 1},
                "devices": [{"name": f"chip-{j}"} for j in range(4)],
            },
        }])
        fake.create(*RES, "resourceclaims", {
            "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
            "metadata": {"name": "c-e2e", "namespace": "default",
                         "uid": "uid-e2e"},
            "spec": {"devices": {"requests": [{
                "name": "tpu",
                "exactly": {"deviceClassName": "tpu.dra.dev"},
            }]}},
        }, namespace="default")
        return fake

    def test_single_trace_spans_scheduler_and_plugin(
            self, fresh_tracing, tmp_path):
        exporter, recorder = fresh_tracing
        fake = self._cluster()
        sm = SchedulerMetrics()
        sched = DraScheduler(fake, sched_metrics=sm)
        sched.sync_once()

        claim = fake.get(*RES, "resourceclaims", "c-e2e",
                         namespace="default")
        assert claim["status"]["allocation"]
        header = claim["metadata"]["annotations"][
            tracing.TRACEPARENT_ANNOTATION]
        ctx = tracing.SpanContext.from_traceparent(header)
        assert ctx is not None and ctx.sampled

        # REAL node-side prepare off the allocated claim object.
        state = DeviceState(Config.mock(root=str(tmp_path)))
        rc = ResourceClaim.from_dict(claim)
        assert rc.annotations[tracing.TRACEPARENT_ANNOTATION] == header
        ids = state.prepare(rc)
        assert ids

        trace = exporter.traces()[ctx.trace_id]
        by_name = {}
        for doc in trace:
            by_name.setdefault(doc["name"], doc)
        # One trace id covers the scheduler AND the plugin.
        assert "sched.commit" in by_name
        assert "prepare" in by_name
        assert "prep_devices" in by_name
        # The plugin's operation span is a CHILD of the commit span
        # (the annotation carried the commit span id).
        assert by_name["sched.commit"]["span_id"] == ctx.span_id
        assert by_name["prepare"]["parent_id"] == ctx.span_id
        assert by_name["prep_devices"]["parent_id"] == \
            by_name["prepare"]["span_id"]

        # SLO histogram: control-plane phases landed with samples.
        phases = set()
        for metric in sm.slo.e2e.collect():
            for s in metric.samples:
                if s.name.endswith("_count") and s.value > 0:
                    phases.add(s.labels["phase"])
        assert {"fit", "commit", "patch"} <= phases

        # Flight recorder has the claim's cross-binary timeline.
        events = {e["event"] for e in recorder.events("uid-e2e")}
        assert {"fit", "alloc_patched", "prepare_segments"} <= events

        state.unprepare("uid-e2e")

    def test_trace_served_over_http(self, fresh_tracing, tmp_path):
        exporter, recorder = fresh_tracing
        fake = self._cluster()
        sched = DraScheduler(fake)
        sched.sync_once()
        claim = fake.get(*RES, "resourceclaims", "c-e2e",
                         namespace="default")
        ctx = tracing.SpanContext.from_traceparent(
            claim["metadata"]["annotations"][
                tracing.TRACEPARENT_ANNOTATION])
        state = DeviceState(Config.mock(root=str(tmp_path)))
        state.prepare(ResourceClaim.from_dict(claim))

        from prometheus_client import CollectorRegistry

        server = MetricsServer(CollectorRegistry(), host="127.0.0.1",
                               port=0)
        server.start()
        try:
            port = server.port
            doc = _http_json(port, "/debug/traces")
            assert ctx.trace_id in doc["traces"]
            names = {s["name"] for s in doc["traces"][ctx.trace_id]}
            assert {"sched.commit", "prepare"} <= names
            one = _http_json(port, f"/debug/traces/{ctx.trace_id}")
            assert {s["name"] for s in one["spans"]} == names
            claims = _http_json(port, "/debug/claims/uid-e2e")
            assert any(e["event"] == "prepare_segments"
                       for e in claims["events"])
            index = _http_json(port, "/debug/claims")
            assert "uid-e2e" in index["claims"]
            with pytest.raises(urllib.error.HTTPError):
                _http_json(port, "/debug/traces/feedfacefeedface"
                                 "feedfacefeedface")
        finally:
            server.stop()

    def test_sampling_off_stamps_nothing(self, fresh_tracing,
                                         monkeypatch):
        exporter, _ = fresh_tracing
        monkeypatch.setenv(tracing.ENV_SAMPLE, "0")
        fake = self._cluster()
        sched = DraScheduler(fake)
        sched.sync_once()
        claim = fake.get(*RES, "resourceclaims", "c-e2e",
                         namespace="default")
        assert claim["status"]["allocation"]
        assert tracing.TRACEPARENT_ANNOTATION not in (
            claim["metadata"].get("annotations") or {})
        assert exporter.spans() == []

    def test_stale_traceparent_cleared_on_unsampled_realloc(
            self, fresh_tracing, monkeypatch):
        """A claim re-allocated with an UNSAMPLED commit must not keep
        a previous allocation's traceparent (eviction -> migration):
        the commit patch clears it, or the node plugin would parent
        the new prepare under the dead first trace."""
        exporter, _ = fresh_tracing
        monkeypatch.setenv(tracing.ENV_SAMPLE, "0")
        fake = self._cluster()
        stale = tracing.SpanContext(trace_id="ab" * 16,
                                    span_id="cd" * 8)
        fake.patch(*RES, "resourceclaims", "c-e2e",
                   {"metadata": {"annotations": {
                       tracing.TRACEPARENT_ANNOTATION:
                           stale.to_traceparent()}}},
                   namespace="default")
        sched = DraScheduler(fake)
        sched.sync_once()
        claim = fake.get(*RES, "resourceclaims", "c-e2e",
                         namespace="default")
        assert claim["status"]["allocation"]
        assert tracing.TRACEPARENT_ANNOTATION not in (
            claim["metadata"].get("annotations") or {})
        assert exporter.spans() == []
