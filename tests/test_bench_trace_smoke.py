"""Tier-1 tracing-overhead smoke: the `make bench-trace-smoke`
contract as a non-slow test. Runs `bench.py --trace-overhead` on a
shrunk trace and asserts (a) fully-sampled claim-lifecycle tracing
stays inside the 5% overhead envelope of the tracing-off wall clock
(min-of-interleaved-reps ratio, adaptively extended with more reps
under load, so a loaded CI box doesn't decide the gate), (b) the
sampling knob actually gates the hot path -- sampling
on exports spans, sampling off exports ZERO, (c) the traced
event-driven churn converges every claim, and (d) the
BENCH_observability.json artifact is emitted -- so a tracing hot-path
regression fails fast here instead of surfacing as a BENCH trajectory
dip."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Keep in sync with the Makefile bench-trace-smoke target.
SMOKE_ENV = {
    "BENCH_TRACE_NODES": "8",
    "BENCH_TRACE_CLAIMS": "64",
    "BENCH_TRACE_REPS": "4",
    "BENCH_TRACE_CHURN_CLAIMS": "24",
    "BENCH_TRACE_MAX_OVERHEAD_PCT": "5",
}


def test_trace_overhead_smoke(tmp_path):
    out_file = str(tmp_path / "BENCH_observability.json")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--trace-overhead"],
        env={**os.environ, "PYTHONPATH": REPO, **SMOKE_ENV,
             "BENCH_OBS_OUT": out_file},
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["metric"] == "trace_overhead_pct"
    ex = doc["extras"]
    # The overhead gate itself (bench exits nonzero past the cap; the
    # assert keeps the number visible in the pytest failure too).
    assert doc["value"] <= 5.0
    # The sampling knob gates span export BOTH ways: on must trace the
    # real control plane, off must export nothing at all.
    assert ex["trace_spans_exported_on"] > 0
    assert ex["trace_churn_spans_on"] > 0
    assert ex["trace_spans_exported_off"] == 0
    # The traced event-driven churn still converged every claim.
    assert ex["trace_unconverged"] == 0
    # The trajectory artifact landed and round-trips.
    with open(out_file, encoding="utf-8") as f:
        emitted = json.load(f)
    assert emitted["metric"] == "trace_overhead_pct"
    assert emitted["extras"]["trace_spans_exported_off"] == 0
