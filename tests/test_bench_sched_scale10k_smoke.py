"""Tier-1 10k-scale smoke: the `make bench-sched-scale10k-smoke`
contract as a non-slow test. Runs `bench.py --sched-scale` on the
shrunk deterministic trace and asserts the PR 11 gates:

- per-pool snapshot DELTA rebuild beats the cold full rebuild (>=1.5x
  at smoke scale; >=5x gated at the full 10k run) with byte-identical
  candidate sets at every churn event,
- identical final allocations vs workers=1 on the pinned trace (the
  delta path must not change WHAT gets allocated),
- a claim pinned to an exhausted scheduling domain SPILLS to its
  sibling domain (annotated intent + deduped DomainSpilled event)
  while the opt-out annotation is respected,
- writes/claim and convergence stay within the scale envelope,
- the result lands as the `scale10k` trajectory entry.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Keep in sync with the Makefile bench-sched-scale10k-smoke target.
SMOKE_ENV = {
    "BENCH_SCALE_ENTRY": "scale10k",
    "BENCH_SCALE_NODES": "60",
    "BENCH_SCALE_CLAIMS": "180",
    "BENCH_SCALE_BURST": "60",
    "BENCH_SCALE_WORKERS": "4",
    "BENCH_SCALE_BATCH": "16",
    "BENCH_SCALE_PIN": "1",
    "BENCH_SCALE_REQUIRE_IDENTICAL": "1",
    "BENCH_SCALE_MAX_WRITES_PER_CLAIM": "3.5",
    "BENCH_SCALE_MAX_P99_MS": "5000",
    "BENCH_SCALE_DELTA_NODES": "300",
    "BENCH_SCALE_MIN_DELTA_SPEEDUP": "1.5",
    "BENCH_SCALE_REQUIRE_SPILLOVER": "1",
}


def test_sched_scale10k_smoke(tmp_path):
    out_file = str(tmp_path / "BENCH_scheduler.json")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--sched-scale"],
        env={**os.environ, "PYTHONPATH": REPO, **SMOKE_ENV,
             "BENCH_SCHED_OUT": out_file},
        capture_output=True, text=True, timeout=600, cwd=REPO,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    ex = doc["extras"]
    # Correctness: deterministic equivalence + the scale envelope.
    assert ex["scale_identical_allocations"] is True
    for w in (1, 4):
        assert ex[f"scale_w{w}_unconverged"] == 0
        assert ex[f"scale_w{w}_double_allocated"] == 0
        assert ex[f"scale_w{w}_writes_per_claim"] <= 3.5
    # The per-pool delta maintenance contract: faster than a cold
    # rebuild AND byte-identical to it at every churn event.
    assert ex["scale_delta_speedup"] >= 1.5
    assert ex["scale_delta_equiv_mismatches"] == 0
    assert ex["scale_delta_pool_builds"] > 0
    # The spillover contract: the pinned claim escaped its exhausted
    # domain; the opted-out claim stayed put with the condition.
    assert ex["scale_spillover_proven"] is True
    assert ex["scale_spillover_optout_respected"] is True
    assert ex["scale_spillover_events"] == 1
    # The trajectory artifact landed under its own entry key,
    # alongside (never clobbering) the churn/scale entries.
    with open(out_file, encoding="utf-8") as f:
        emitted = json.load(f)
    assert emitted["scale10k"]["extras"]["scale_delta_speedup"] == \
        ex["scale_delta_speedup"]
