"""CEL-subset evaluator: grammar coverage + every shipped selector
evaluated against devices the drivers really publish (the executable
upgrade of test_cel_attribute_consistency's static cross-check)."""

import os
import re

import pytest

from k8s_dra_driver_gpu_tpu.pkg.cel import (
    CelEvalError,
    CelParseError,
    Quantity,
    compile_expression,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ev(expr, env=None):
    return compile_expression(expr).evaluate(env or {})


class TestGrammar:
    def test_literals_and_bool_ops(self):
        assert ev("true && !false") is True
        assert ev("false || true") is True
        assert ev('("a" == "a") && (1 != 2)') is True

    def test_comparisons(self):
        assert ev("3 >= 2") and ev("2 <= 2") and not ev("1 > 1")
        assert ev("1.5 < 2")

    def test_type_mismatch_is_error_not_false(self):
        with pytest.raises(CelEvalError):
            ev('1 == "1"')
        with pytest.raises(CelEvalError):
            ev("true == 1")

    def test_member_index_in(self):
        env = {"device": {
            "driver": "d",
            "attributes": {"d": {"platform": {"string": "v5e"},
                                 "iciX": {"int": "3"},
                                 "healthy": {"bool": True}}},
        }}
        assert ev('device.driver == "d"', env)
        assert ev('device.attributes["d"].platform == "v5e"', env)
        assert ev('device.attributes["d"].iciX >= 3', env)
        assert ev('device.attributes["d"].healthy', env)
        assert ev('"platform" in device.attributes["d"]', env)
        assert not ev('"nope" in device.attributes["d"]', env)

    def test_missing_key_is_error_absorbed_by_and(self):
        env = {"device": {"driver": "other", "attributes": {}}}
        # attributes["d"] errors, but the left false absorbs it.
        assert ev('device.driver == "d" && '
                  'device.attributes["d"].x == 1', env) is False
        with pytest.raises(CelEvalError):
            ev('device.attributes["d"].x == 1', env)

    def test_or_absorbs_error_when_true(self):
        env = {"device": {"driver": "d", "attributes": {}}}
        assert ev('device.driver == "d" || '
                  'device.attributes["d"].x == 1', env) is True

    def test_version_attributes_compare_semver_not_lexically(self):
        env = {"device": {
            "driver": "d",
            "attributes": {"d": {"ver": {"version": "10.0.0"}}},
        }}
        # Lexicographic would say "10.0.0" < "9.0.0"; semver must not.
        assert ev('device.attributes["d"].ver >= "9.0.0"', env)
        assert ev('device.attributes["d"].ver == "10.0.0"', env)
        assert ev('device.attributes["d"].ver < "10.1.0-rc1"', env)
        assert ev('device.attributes["d"].ver.compareTo('
                  'semver("10.0.1")) < 0', env)
        # Pre-release sorts before its release.
        pre = {"device": {"driver": "d", "attributes": {
            "d": {"ver": {"version": "2.0.0-beta"}}}}}
        assert ev('device.attributes["d"].ver < "2.0.0"', pre)

    def test_string_methods(self):
        env = {"s": "tpu-v5p-8"}
        assert ev('s.startsWith("tpu")', env)
        assert ev('s.endsWith("-8")', env)
        assert ev('s.contains("v5p")', env)
        assert ev('s.matches("v5[ep]")', env)

    def test_parse_errors_are_loud(self):
        for bad in ("device.attributes[", "a ? b : c", "x @ y", "1 +"):
            with pytest.raises(CelParseError):
                compile_expression(bad)


class TestQuantity:
    def test_parse_and_compare(self):
        assert Quantity.parse("1Ki").milli == 1024 * 1000
        assert Quantity.parse("1.5Gi").compare_to(
            Quantity.parse("1536Mi")) == 0
        assert Quantity.parse("2G").compare_to(Quantity.parse("2Gi")) < 0
        assert Quantity.parse("500m").compare_to(Quantity.parse("1")) < 0
        assert Quantity.parse("129e6").as_integer() == 129_000_000

    def test_capacity_compare_to(self):
        env = {"device": {
            "driver": "d",
            "capacity": {"d": {"hbmBytes": {"value": "34359738368"}}},
        }}
        assert ev('device.capacity["d"].hbmBytes.compareTo('
                  'quantity("30Gi")) >= 0', env)
        assert ev('device.capacity["d"].hbmBytes.isGreaterThan('
                  'quantity("1Gi"))', env)
        assert not ev('device.capacity["d"].hbmBytes.isLessThan('
                      'quantity("1Gi"))', env)


def shipped_expressions() -> list[str]:
    """Every CEL expression in the chart, demo specs, and e2e tier."""
    exprs = []
    pat = re.compile(r'expression:\s*(.+)')
    roots = ["deployments", "demo"]
    for root in roots:
        for dirpath, _, files in os.walk(os.path.join(REPO, root)):
            for f in files:
                if not f.endswith((".yaml", ".yml")):
                    continue
                text = open(os.path.join(dirpath, f),
                            encoding="utf-8").read()
                for m in pat.finditer(text):
                    e = m.group(1).strip()
                    if e.startswith(">"):
                        continue  # folded block; VAP policy, not device CEL
                    if e.startswith("device."):
                        exprs.append(e)
    assert exprs, "no shipped selectors found"
    return sorted(set(exprs))


class TestShippedSelectors:
    """Compile every shipped selector; evaluate each against real
    published devices and assert each matches at least one device of
    its own driver and none of the other driver's."""

    @pytest.fixture(scope="class")
    def published(self, tmp_path_factory):
        from k8s_dra_driver_gpu_tpu.kubeletplugin.device_state import (
            Config,
            DeviceState,
        )
        from k8s_dra_driver_gpu_tpu.pkg.featuregates import FeatureGates
        from k8s_dra_driver_gpu_tpu.tpulib.binding import (
            EnumerateOptions,
            PyTpuLib,
        )
        from tests.test_vfio_health import fake_pci_tree

        base = tmp_path_factory.mktemp("cel-pub")
        st = DeviceState(Config.mock(root=str(base), topology="v5p-8"))
        tpu = [(d.to_dra_device(), "tpu.dra.dev")
               for d in st.allocatable.values()]
        bdfs = [c.pci_bdf for c in PyTpuLib().enumerate(
            EnumerateOptions(mock_topology="v5e-4")).chips]
        sys_root = fake_pci_tree(base / "pt", bdfs)
        pt = DeviceState(Config(
            root=str(base / "pt" / "state"),
            tpulib_opts=EnumerateOptions(
                mock_topology="v5e-4", sys_root=sys_root,
                dev_root=str(base / "pt" / "dev")),
            feature_gates=FeatureGates.parse("PassthroughSupport=true"),
            cdi_root=str(base / "pt" / "cdi"),
            tenancy_agents=False,
        ))
        tpu += [(d.to_dra_device(), "tpu.dra.dev")
                for d in pt.allocatable.values()]
        from k8s_dra_driver_gpu_tpu.computedomain.plugin.device_state import (
            CDDeviceState,
        )
        from k8s_dra_driver_gpu_tpu.pkg.kubeclient import FakeKubeClient
        cd = CDDeviceState(str(base / "cd"), FakeKubeClient(), "n0",
                           use_informer=False)
        cddevs = [(d, "compute-domain.tpu.dra.dev")
                  for d in cd.allocatable_devices()]
        return tpu + cddevs

    def test_all_compile(self):
        for expr in shipped_expressions():
            compile_expression(expr)

    def test_each_matches_only_its_driver(self, published):
        for expr in shipped_expressions():
            prog = compile_expression(expr)
            own_driver = re.search(r'"([^"]*dra[^"]*)"', expr).group(1)
            hits = [drv for dev, drv in published
                    if prog.matches_device(dev, drv)]
            if "profile" in expr and "v5p" not in expr and \
                    "==" in expr.split("&&")[-1]:
                # profile == "1c"/"2x1x1" demo selectors may target a
                # topology this mock doesn't carve; compile-only there.
                continue
            assert hits, f"selector matched nothing: {expr}"
            assert all(h == own_driver or "device.driver" not in expr
                       for h in hits), (expr, hits)


class TestCompileMemoization:
    def test_ast_shared_across_programs(self):
        """compile_expression memoizes the parsed AST by source text:
        two programs for the same expression (e.g. the same selector
        evaluated for every candidate device, pass after pass) share
        one immutable AST instead of re-lexing/re-parsing."""
        expr = 'device.driver == "tpu.dra.dev"'
        p1 = compile_expression(expr)
        p2 = compile_expression(expr)
        assert p1._ast is p2._ast
        assert p1.evaluate({"device": {"driver": "tpu.dra.dev"}}) is True
        assert p2.evaluate({"device": {"driver": "other"}}) is False

    def test_parse_failure_not_cached_as_success(self):
        bad = 'device.driver =='
        with pytest.raises(CelParseError):
            compile_expression(bad)
        with pytest.raises(CelParseError):
            compile_expression(bad)

    def test_scheduler_selector_cache_shared_across_instances(self):
        from k8s_dra_driver_gpu_tpu.pkg.scheduler import _CompiledSelectors

        expr = 'device.driver == "tpu.dra.dev"'
        s1, s2 = _CompiledSelectors(), _CompiledSelectors()
        assert s1.get(expr) is s2.get(expr)
        # A broken selector is negatively cached (matches nothing).
        assert s1.get("device.driver ==") is None
        assert s2.get("device.driver ==") is None
