"""Event-driven incremental scheduler tier (pkg/scheduler +
pkg/schedcache): dirty-set sync, indexed snapshot lifecycle, and the
three proofs ISSUE 5 demands --

- **no-op steady state**: a quiesced cluster performs ZERO kube writes
  (and, in event mode, zero kube reads) across 10 sync drains
  including forced full safety resyncs;
- **incremental-vs-full equivalence**: the same recorded churn trace
  produces IDENTICAL final allocations under the polled full-resync
  loop and the event-driven dirty-set loop;
- **snapshot invalidation**: the inventory snapshot is reused while
  slices are untouched and rebuilt on any slice write / pool-generation
  bump, with the incremental allocation state rebuilt alongside it.
"""

import time

import pytest

from k8s_dra_driver_gpu_tpu.pkg.kubeclient import FakeKubeClient
from k8s_dra_driver_gpu_tpu.pkg.metrics import SchedulerMetrics
from k8s_dra_driver_gpu_tpu.pkg.schedcache import (
    AllocationState,
    ClusterView,
    InventorySnapshot,
)
from k8s_dra_driver_gpu_tpu.pkg.scheduler import DraScheduler
from k8s_dra_driver_gpu_tpu.pkg.sliceutil import publish_resource_slices

from tests.fake_kube import CountingKube

RES = ("resource.k8s.io", "v1")


def apply_class(kube, name="tpu.dra.dev"):
    kube.create(*RES, "deviceclasses", {
        "apiVersion": "resource.k8s.io/v1", "kind": "DeviceClass",
        "metadata": {"name": name},
        "spec": {"selectors": [{"cel": {
            "expression": f'device.driver == "{name}"'}}]},
    })


def node_slices(node, chips=4, driver="tpu.dra.dev", taints=None):
    devices = []
    for j in range(chips):
        dev = {"name": f"chip-{j}", "attributes": {
            "type": {"string": "tpu-chip"}, "index": {"int": j}}}
        if taints and j in taints:
            dev["taints"] = list(taints[j])
        devices.append(dev)
    return [{
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceSlice",
        "metadata": {"name": f"{node}-{driver}"},
        "spec": {"driver": driver, "nodeName": node,
                 "pool": {"name": node, "generation": 1,
                          "resourceSliceCount": 1},
                 "devices": devices},
    }]


def make_claim(kube, name, count=1, ns="default", cel=None):
    exactly = {"deviceClassName": "tpu.dra.dev"}
    if count != 1:
        exactly["count"] = count
    if cel:
        exactly["selectors"] = [{"cel": {"expression": cel}}]
    kube.create(*RES, "resourceclaims", {
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"devices": {"requests": [
            {"name": "tpu", "exactly": exactly}]}},
    }, namespace=ns)


def make_pod(kube, name, claim_name, ns="default"):
    kube.create("", "v1", "pods", {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"containers": [{"name": "c"}],
                 "resourceClaims": [{"name": "tpu",
                                     "resourceClaimName": claim_name}]},
    }, namespace=ns)


def allocation(kube, name, ns="default"):
    return kube.get(*RES, "resourceclaims", name, ns).get(
        "status", {}).get("allocation")


def wait_for(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.005)
    return False


@pytest.fixture()
def event_sched():
    """(counting kube, event-driven scheduler) over a 2-node x 4-chip
    inventory; the scheduler writes through the counter, the trace
    mutations go straight to the fake."""
    fake = FakeKubeClient()
    apply_class(fake)
    for node in ("node-a", "node-b"):
        publish_resource_slices(fake, node_slices(node))
    counting = CountingKube(fake)
    sched = DraScheduler(counting, sched_metrics=SchedulerMetrics())
    sched.start_event_driven()
    assert sched.drain(15.0)
    try:
        yield fake, counting, sched
    finally:
        sched.stop()


class TestEventDrivenFlow:
    def test_claim_event_allocates_and_binds_pod(self, event_sched):
        fake, counting, sched = event_sched
        make_claim(fake, "c1")
        make_pod(fake, "p1", "c1")
        assert sched.drain(15.0)
        assert wait_for(lambda: allocation(fake, "c1"))
        assert wait_for(lambda: fake.get("", "v1", "pods", "p1",
                                         "default")["spec"].get(
            "nodeName"))
        claim = fake.get(*RES, "resourceclaims", "c1", "default")
        assert claim["status"]["reservedFor"][0]["name"] == "p1"

    def test_template_pod_generates_claim_event_driven(self, event_sched):
        fake, counting, sched = event_sched
        fake.create(*RES, "resourceclaimtemplates", {
            "apiVersion": "resource.k8s.io/v1",
            "kind": "ResourceClaimTemplate",
            "metadata": {"name": "tpl", "namespace": "default"},
            "spec": {"spec": {"devices": {"requests": [
                {"name": "tpu",
                 "exactly": {"deviceClassName": "tpu.dra.dev"}}]}}},
        }, namespace="default")
        fake.create("", "v1", "pods", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "worker", "namespace": "default"},
            "spec": {"containers": [{"name": "c"}],
                     "resourceClaims": [{
                         "name": "tpu",
                         "resourceClaimTemplateName": "tpl"}]},
        }, namespace="default")
        assert sched.drain(15.0)

        def bound():
            pod = fake.get("", "v1", "pods", "worker", "default")
            return pod["spec"].get("nodeName")
        assert wait_for(bound)
        pod = fake.get("", "v1", "pods", "worker", "default")
        generated = pod["status"]["resourceClaimStatuses"][0][
            "resourceClaimName"]
        assert allocation(fake, generated)

    def test_claim_delete_unblocks_pending_claim(self, event_sched):
        fake, counting, sched = event_sched
        # 8 chips total; c-big takes 8, c-wait must pend.
        make_claim(fake, "c-big-a", count=4)
        make_claim(fake, "c-big-b", count=4)
        make_claim(fake, "c-wait")
        assert sched.drain(15.0)
        assert wait_for(lambda: allocation(fake, "c-big-a"))
        assert wait_for(lambda: allocation(fake, "c-big-b"))
        assert allocation(fake, "c-wait") is None
        fake.delete(*RES, "resourceclaims", "c-big-a", "default")
        assert sched.drain(15.0)
        assert wait_for(lambda: allocation(fake, "c-wait"))

    def test_slice_publish_retries_pending_claims(self, event_sched):
        fake, counting, sched = event_sched
        make_claim(fake, "c-gpu", cel=(
            'device.attributes["tpu.dra.dev"].index == 9'))
        assert sched.drain(15.0)
        assert allocation(fake, "c-gpu") is None
        # A new node appears whose chip-9 satisfies the selector.
        publish_resource_slices(fake, node_slices("node-c", chips=10))
        assert sched.drain(15.0)
        assert wait_for(lambda: allocation(fake, "c-gpu"))


class TestNoOpSteadyState:
    def test_quiesced_cluster_zero_kube_traffic_over_10_drains(
            self, event_sched):
        """The satellite proof: once converged, 10 sync drains --
        including forced FULL safety resyncs -- perform ZERO kube
        writes (and in event mode, zero reads: everything comes from
        the informer caches)."""
        fake, counting, sched = event_sched
        for i in range(3):
            make_claim(fake, f"c{i}")
            make_pod(fake, f"p{i}", f"c{i}")
        assert sched.drain(15.0)
        assert wait_for(lambda: all(
            allocation(fake, f"c{i}") for i in range(3)))
        assert wait_for(lambda: all(
            fake.get("", "v1", "pods", f"p{i}", "default")["spec"].get(
                "nodeName") for i in range(3)))
        assert sched.drain(15.0)
        writes0, reads0 = counting.writes, counting.reads
        for _ in range(10):
            sched._enqueue(("full",))
            assert sched.drain(15.0)
        assert counting.writes == writes0, \
            "a quiesced cluster must cost zero kube writes"
        assert counting.reads == reads0, \
            "event mode must serve full resyncs from informer caches"


class TestIncrementalFullEquivalence:
    # A recorded churn trace: creations (with varying counts and a
    # selector), interleaved deletions, then a final wave. Both
    # schedulers must land on IDENTICAL final allocations.
    TRACE = [
        ("create", "a", {"count": 2}),
        ("create", "b", {"count": 1}),
        ("create", "c", {"count": 1,
                         "cel": 'device.attributes["tpu.dra.dev"]'
                                '.index == 0'}),
        ("delete", "b", None),
        ("create", "d", {"count": 3}),
        ("create", "e", {"count": 1}),
        ("delete", "a", None),
        ("create", "f", {"count": 2}),
        ("create", "g", {"count": 4}),
    ]

    @staticmethod
    def _setup(fake):
        apply_class(fake)
        for node in ("node-a", "node-b"):
            publish_resource_slices(fake, node_slices(node))

    @staticmethod
    def _final_allocations(fake):
        out = {}
        for claim in fake.objects("resource.k8s.io", "resourceclaims"):
            alloc = claim.get("status", {}).get("allocation")
            name = claim["metadata"]["name"]
            if alloc is None:
                out[name] = None
                continue
            out[name] = sorted(
                (r["pool"], r["device"])
                for r in alloc["devices"]["results"])
        return out

    def _apply(self, fake, op, name, kw, settle):
        if op == "create":
            make_claim(fake, name, count=kw.get("count", 1),
                       cel=kw.get("cel"))
        else:
            fake.delete(*RES, "resourceclaims", name, "default")
        settle()

    def test_same_final_allocations(self):
        polled = FakeKubeClient()
        self._setup(polled)
        sched_p = DraScheduler(polled)
        for op, name, kw in self.TRACE:
            self._apply(polled, op, name, kw,
                        settle=lambda: (sched_p.sync_once(),
                                        sched_p.sync_once()))

        evented = FakeKubeClient()
        self._setup(evented)
        sched_e = DraScheduler(evented)
        sched_e.start_event_driven()
        assert sched_e.drain(15.0)
        try:
            for op, name, kw in self.TRACE:
                self._apply(evented, op, name, kw,
                            settle=lambda: sched_e.drain(15.0))
        finally:
            sched_e.stop()

        got_p = self._final_allocations(polled)
        got_e = self._final_allocations(evented)
        assert got_p == got_e, (got_p, got_e)
        # And the trace exercised real allocation: everything final is
        # allocated (capacity: 8 chips; live demand at the end: 1+3+1+
        # 2 = 7 plus g's 4 won't fit -> g pends identically).
        assert got_p["g"] is None
        assert all(got_p[n] for n in ("c", "d", "e", "f"))


class TestSnapshotLifecycle:
    def test_snapshot_cached_until_slice_change(self):
        fake = FakeKubeClient()
        publish_resource_slices(fake, node_slices("node-a"))
        view = ClusterView(fake)
        s1 = view.snapshot()
        assert {c.name for c in s1.candidates} == {
            "chip-0", "chip-1", "chip-2", "chip-3"}
        assert view.snapshot() is s1  # nothing changed: same object
        # An unchanged diffed republish performs no writes -> the
        # snapshot (and its selector/topology memos) survives.
        stats = publish_resource_slices(fake, node_slices("node-a"))
        assert stats["writes"] == 0
        assert view.snapshot() is s1

    def test_snapshot_rebuilt_on_pool_generation_bump(self):
        fake = FakeKubeClient()
        publish_resource_slices(fake, node_slices("node-a"))
        view = ClusterView(fake)
        s1 = view.snapshot()
        s1.order_cache[("sentinel",)] = ["stale"]
        # Device inventory change -> generation bump -> new snapshot,
        # fresh memos.
        publish_resource_slices(fake, node_slices("node-a", chips=5))
        s2 = view.snapshot()
        assert s2 is not s1
        assert ("sentinel",) not in s2.order_cache
        assert "chip-4" in {c.name for c in s2.candidates}
        assert s2.pool_generations[("tpu.dra.dev", "node-a")] == 2

    def test_stale_generation_filtered_from_snapshot(self):
        fake = FakeKubeClient()
        publish_resource_slices(fake, node_slices("node-a"))
        stale = node_slices("node-a")[0]
        stale["metadata"]["name"] = "stale"
        stale["spec"]["pool"]["generation"] = 0
        stale["spec"]["devices"] = [{"name": "phantom"}]
        fake.create(*RES, "resourceslices", stale)
        snap = ClusterView(fake).snapshot()
        assert "phantom" not in {c.name for c in snap.candidates}

    def test_default_node_fallback_for_nodeless_slices(self):
        # Cluster-scoped (nodeName-less) slices bucket under the
        # scheduler's --default-node so bound-pod pins can still match.
        fake = FakeKubeClient()
        nodeless = node_slices("node-a")[0]
        del nodeless["spec"]["nodeName"]
        fake.create(*RES, "resourceslices", nodeless)
        snap = ClusterView(fake, default_node="node-dflt").snapshot()
        assert set(snap.by_node) == {"node-dflt"}
        assert ClusterView(fake).snapshot().by_node.keys() == {""}

    def test_allocation_state_observe_idempotent_and_forget(self):
        snap = InventorySnapshot(node_slices("node-a"))
        alloc = AllocationState(snap)
        claim = {
            "metadata": {"uid": "u1", "namespace": "default",
                         "name": "c1"},
            "status": {"allocation": {"devices": {"results": [{
                "driver": "tpu.dra.dev", "pool": "node-a",
                "device": "chip-0"}]}}},
        }
        assert alloc.observe(claim) is True
        assert alloc.observe(claim) is False  # replay: no-op
        assert ("tpu.dra.dev", "node-a", "chip-0") in alloc.allocated
        assert alloc.forget(claim) is True
        assert not alloc.allocated
        assert alloc.forget(claim) is False


class TestSchedulerMetricsWiring:
    def test_sync_histogram_and_queue_depth_exported(self, event_sched):
        from prometheus_client import generate_latest

        fake, counting, sched = event_sched
        make_claim(fake, "c1")
        assert sched.drain(15.0)
        text = generate_latest(sched.sched_metrics.registry).decode()
        assert 'tpu_dra_sched_sync_seconds_count{mode="full"}' in text
        assert 'mode="incremental"' in text
        assert "tpu_dra_sched_dirty_queue_depth" in text
        assert "tpu_dra_informer_relist_total" in text
